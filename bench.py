"""Benchmark harness — prints ONE JSON line with the primary metric.

Primary metric (BASELINE.md): SVGD particle-updates/sec **plus
steps-to-target-accuracy** on distributed Bayesian logistic regression
(banana fold 42).  The reference's published numbers (notes.md:120-135,
reproduced in BASELINE.md) top out at **421 updates/sec** at world size 8
(50 particles, 500 iterations, CPU); world size 1 is 12.5 up/s.
``vs_baseline`` is measured-updates/sec divided by the reference's best (421).

The headline number runs the **north-star path** (BASELINE.json): the 10k
particle array sharded over 8 shards in ``all_particles`` exchange mode —
each shard updates its block against the ``lax.all_gather``-ed global set —
driven through ``DistSampler.run_steps`` (one ``lax.scan`` dispatch for the
whole trajectory).  On the single-chip pool this executes the identical SPMD
program under vmap emulation — an honest single-chip number.  Round-2
interleaved A/B measurement put the emulated sharded step at parity with the
unsharded one (wall ratio 0.82–1.16 across repeats, within the pool's noise
band; the round-1 "2× emulation gap" did not reproduce — docs/notes.md).
The unsharded single-device number is reported alongside for context.

The convergence half of the metric runs the same 10k-particle config until
the ensemble posterior-predictive accuracy reaches the sklearn
LogisticRegression baseline − 0.01 (the reference's acceptance comparison,
experiments/logreg_plots.py:37-57) and reports ``steps_to_target_acc`` /
``wall_to_target_acc_s``.  Compile time is excluded by warming the scan,
then resetting the sampler state via ``state_dict``/``load_state_dict``.

Timing is the best of 3 fenced samples, each the mean wall of an
adaptively-sized chain of state-chained scan runs under one trailing fetch
(~1 s of device work per sample, so the tunnel's fixed ~0.1 s per-sample
round trip amortises away — the round-3 protocol; the TPU pool behind the
tunnel has ±40% session variance with within-session spikes, and per-call
eager timing is round-trip-bound and useless — docs/notes.md and
``_timed_chain``).
"""

import json
import sys
import time


REFERENCE_BEST_UPDATES_PER_SEC = 421.0  # notes.md:129 (ws=8) via BASELINE.md
N_PARTICLES = 10_000
N_ITERS = 500
NUM_SHARDS = 8

TARGET_ACC_MARGIN = 0.01   # target = sklearn baseline − margin
CONV_STEP_SIZE = 0.3       # fastest measured stepsize for this config: the
                           # deterministic seed-0 trajectory reaches target
                           # at step 10 (0.1 → 55, 0.2 → 20, 0.5 → 20 —
                           # stability margin on both sides)
CONV_EVAL_EVERY = 5        # steps between accuracy checks (one scan program).
                           # The detection loop only finds S = steps-to-
                           # target; wall_to_target is then re-measured as
                           # S-step scanned dispatches with no eval fetches
                           # (pure trajectory cost, _timed_chain protocol)
CONV_MAX_STEPS = 2_000


def _init_platform():
    """Prefer the real TPU; fall back to CPU (honestly labelled) when the
    chip pool is unavailable."""
    import jax

    try:
        devs = jax.devices()
        return jax.devices()[0].platform, devs
    except Exception as e:  # TPU pool unavailable — rerun on CPU
        print(f"[bench] default backend failed ({type(e).__name__}); CPU fallback", file=sys.stderr)
        from dist_svgd_tpu.utils.platform import force_cpu_backend

        force_cpu_backend()
        return "cpu", jax.devices()


def _fence(x):
    """Force completion with a real device→host round trip.

    ``block_until_ready`` alone is NOT a reliable fence through the axon
    tunnel: the first post-warmup call can return immediately while the scan
    is still in flight (measured: block 0.00 s, then a 3.8 s fetch).  A
    scalar fetch cannot lie."""
    import numpy as np

    np.asarray(x)[0, 0]


#: Fixed per-fenced-sample tunnel round trip (dispatch RPC + scalar fetch),
#: measured ~0.06–0.1 s on the axon relay regardless of workload size
#: (tools/profile_step_floor.py: an empty 1000-iter scan and a single
#: elementwise op cost the same ~95 ms when fenced individually).
_TUNNEL_RT_S = 0.08


def _timed_chain(fn, reps=None, samples=3, target_s=1.0):
    """Best (min) of ``samples`` fenced timings, each the mean wall of
    ``reps`` state-chained runs with one trailing fetch.

    ``fn()`` must return an array whose value depends on the previous call's
    output (e.g. ``run_steps`` advancing sampler state), so the runs execute
    sequentially and cannot be elided.  ``reps=None`` sizes the chain so
    each sample does ~``target_s`` of estimated device work: the tunnel's
    *fixed* per-sample round trip (~0.1 s — dispatch RPC + scalar fetch,
    the same for an empty scan and a 500-step trajectory,
    tools/profile_step_floor.py) then amortises away and the per-rep
    number reflects sustained device throughput rather than RPC latency.
    Round-2 measured a 100-iter small-config dispatch at "0.56 ms/step"
    that this decomposition shows was ≥95% fixed round trip (the marginal
    per-dispatch cost is ~0.2 ms, per-step compute ~2 µs at config-1
    scale).  Chained dispatches pipeline through the relay, so a rep costs
    its execution, not a fresh round trip.  Taking the min across samples
    discards transient slowdowns of the shared TPU pool (±40% between
    sessions, spikes within one — docs/notes.md); the reported number is
    the best *sustained* throughput, still honest because every sample is
    multi-run and fenced."""
    if reps is None:
        # min of 2 estimation runs: a pool spike during a single estimate
        # would mis-size the chain for every sample (the same
        # spike-rejection the timed samples get from min-of-3)
        est = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            _fence(fn())
            est = min(est, time.perf_counter() - t0)  # run + fixed round trip
        marginal = max(est - _TUNNEL_RT_S, 2e-3)
        reps = max(2, min(512, round(target_s / marginal)))
    best = float("inf")
    for _ in range(samples):
        t0 = time.perf_counter()
        out = None
        for _ in range(reps):
            out = fn()
        _fence(out)
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def _make_sharded(fold, phi_impl="auto", wasserstein=False):
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import logreg_logp
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    data = (jnp.asarray(fold.x_train), jnp.asarray(fold.t_train.reshape(-1)))
    d = 1 + fold.x_train.shape[1]
    particles = init_particles_per_shard(0, N_PARTICLES, d, NUM_SHARDS)
    return dt.DistSampler(
        NUM_SHARDS, logreg_logp, None, particles, data=data,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=wasserstein, wasserstein_solver="sinkhorn",
        phi_impl=phi_impl,
    )


def _steps_to_target(fold) -> dict:
    """Run the north-star config until ensemble accuracy ≥ sklearn − margin."""
    import jax
    import jax.numpy as jnp

    from dist_svgd_tpu.models.logreg import ensemble_test_accuracy

    try:
        from sklearn.linear_model import LogisticRegression
    except ImportError:  # pragma: no cover
        return {"steps_to_target_acc": None, "note": "sklearn unavailable"}

    clf = LogisticRegression()
    clf.fit(fold.x_train, fold.t_train.reshape(-1))
    baseline = float(clf.score(fold.x_test, fold.t_test.reshape(-1)))
    target = baseline - TARGET_ACC_MARGIN

    x_test = jnp.asarray(fold.x_test)
    t_test = jnp.asarray(fold.t_test.reshape(-1))
    acc_fn = jax.jit(lambda p: ensemble_test_accuracy(p, x_test, t_test))

    sampler = _make_sharded(fold)
    state0 = sampler.state_dict()
    # warm: compiles the length-CONV_EVAL_EVERY scan and the accuracy eval,
    # then reset to the initial state so the timed loop pays execution only
    sampler.run_steps(CONV_EVAL_EVERY, CONV_STEP_SIZE)
    float(acc_fn(sampler.particles))
    sampler.load_state_dict(state0)

    steps = 0
    acc = float(acc_fn(sampler.particles))
    while steps < CONV_MAX_STEPS:
        sampler.run_steps(CONV_EVAL_EVERY, CONV_STEP_SIZE)
        steps += CONV_EVAL_EVERY
        acc = float(acc_fn(sampler.particles))
        if acc >= target:
            break
    reached = acc >= target

    # wall: S-step scanned dispatches (pure compute — the detection loop's
    # per-eval tunnel fetches are not trajectory cost), _timed_chain
    # protocol (each sample starts from evolving state, so no rep can be
    # relay-cached)
    wall = None
    if reached:
        sampler.load_state_dict(state0)
        run = lambda: sampler.run_steps(steps, CONV_STEP_SIZE)
        _fence(run())  # compile, untimed
        sampler.load_state_dict(state0)
        wall = _timed_chain(run)

    return {
        "sklearn_acc": round(baseline, 4),
        "target_acc": round(target, 4),
        "final_acc": round(acc, 4),
        "steps_to_target_acc": steps if reached else None,
        "wall_to_target_acc_s": None if wall is None else round(wall, 3),
        "conv_step_size": CONV_STEP_SIZE,
    }


def main():
    platform, devs = _init_platform()

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import make_logreg_logp
    from dist_svgd_tpu.utils.datasets import load_benchmark

    fold = load_benchmark("banana", 42)
    d = 1 + fold.x_train.shape[1]
    on_cpu = platform == "cpu"
    n_iters = N_ITERS if not on_cpu else 50  # CPU: measure less, same metric

    # --- headline: the sharded north-star path (BASELINE.json) -----------
    sharded = _make_sharded(fold)
    _fence(sharded.run_steps(n_iters, 3e-3))  # compile, untimed
    wall = _timed_chain(lambda: sharded.run_steps(n_iters, 3e-3))
    sharded_ups = N_PARTICLES * n_iters / wall

    # --- context: the same sharded config on the reduced-precision kernel
    # (opt-in phi_impl='pallas_bf16'; at this small-d shape that is the
    # bf16-exp variant, ~3e-4 phi error — converges to the
    # same accuracy at the bench stepsize, docs/notes.md; reported as
    # context, never as the exact-math headline)
    bf16_ups = None
    if platform == "tpu":  # off-TPU the pallas path runs the interpreter
        sharded16 = _make_sharded(fold, phi_impl="pallas_bf16")
        _fence(sharded16.run_steps(n_iters, 3e-3))
        bf16_wall = _timed_chain(lambda: sharded16.run_steps(n_iters, 3e-3))
        bf16_ups = N_PARTICLES * n_iters / bf16_wall

    # --- the reference's flagship optional term: --wasserstein (JKO) ------
    # (dsvgd/distsampler.py:103-129).  Scanned Sinkhorn path with the
    # warm-started duals (carried g in the scan state); 100 iters is enough
    # to time a per-step cost that is ~25x the plain step's.  TPU only —
    # the CPU fallback would time the backend, not the framework
    w2_ups = w2_ms = None
    if platform == "tpu":
        w2_iters = 100
        w2 = _make_sharded(fold, wasserstein=True)
        _fence(w2.run_steps(w2_iters, 3e-3, h=10.0))  # compile, untimed
        w2_wall = _timed_chain(lambda: w2.run_steps(w2_iters, 3e-3, h=10.0))
        w2_ups = N_PARTICLES * w2_iters / w2_wall
        w2_ms = w2_wall / w2_iters * 1e3

    # --- context: single-device unsharded step ---------------------------
    # reps chain through initial_particles so each run depends on the
    # previous one's output (_timed_chain's precondition: no rep can be
    # elided, overlapped, or served from a relay cache)
    logp = make_logreg_logp(fold.x_train, fold.t_train.reshape(-1))

    def chained_runner(sampler, n, iters):
        state = {"out": None}

        def run_one():
            state["out"] = sampler.run(
                n, iters, 3e-3, seed=0,
                record=False, initial_particles=state["out"],
            )[0]
            return state["out"]

        return run_one

    run_one = chained_runner(dt.Sampler(d, logp), N_PARTICLES, n_iters)
    _fence(run_one())  # compile, untimed
    single_wall = _timed_chain(run_one)
    single_ups = N_PARTICLES * n_iters / single_wall

    # --- reference's exact headline config (50 particles, 500 iters) -----
    small_run = chained_runner(dt.Sampler(d, logp), 50, 500)
    _fence(small_run())
    small_wall = _timed_chain(small_run)

    # --- convergence half of the metric (TPU only — 10k particles on the
    # CPU fallback would take minutes and measure nothing new) ------------
    conv = _steps_to_target(fold) if not on_cpu else {"steps_to_target_acc": None}

    out = {
        "metric": "particle_updates_per_sec (BayesLR banana, 10k particles, "
                  "8-shard all_particles north star)",
        "value": round(sharded_ups, 1),
        "unit": "updates/sec",
        "vs_baseline": round(sharded_ups / REFERENCE_BEST_UPDATES_PER_SEC, 2),
        "platform": platform,
        "n_particles": N_PARTICLES,
        "n_iters_measured": n_iters,
        "num_shards": NUM_SHARDS,
        "emulated_shards": len(devs) < NUM_SHARDS,
        "wall_s": round(wall, 3),
        "sharded_bf16_updates_per_sec": None if bf16_ups is None else round(bf16_ups, 1),
        "w2_sinkhorn_updates_per_sec": None if w2_ups is None else round(w2_ups, 1),
        "w2_sinkhorn_ms_per_step": None if w2_ms is None else round(w2_ms, 2),
        "single_device_updates_per_sec": round(single_ups, 1),
        "single_device_wall_s": round(single_wall, 3),
        "ref_headline_config_wall_s": round(small_wall, 3),
        "ref_headline_config_ref_wall_s": 2007.11,
    }
    out.update(conv)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
