"""Benchmark harness — prints ONE JSON line with the primary metric.

Primary metric (BASELINE.md): SVGD particle-updates/sec on distributed
Bayesian logistic regression (banana fold 42).  The reference's published
numbers (notes.md:120-135, reproduced in BASELINE.md) top out at **421
updates/sec** at world size 8 (50 particles, 500 iterations, CPU); world
size 1 is 12.5 up/s.  ``vs_baseline`` is measured-updates/sec divided by the
reference's best (421) — the north-star config is 10k particles on TPU.

The benchmark runs the same fused jitted step the framework uses everywhere:
one `lax.scan` over SVGD iterations on an HBM-resident (n, d) particle array,
with `vmap(grad(logp))` scores over the full banana training fold.
"""

import json
import sys
import time


REFERENCE_BEST_UPDATES_PER_SEC = 421.0  # notes.md:129 (ws=8) via BASELINE.md
N_PARTICLES = 10_000
N_ITERS = 500


def _init_platform():
    """Prefer the real TPU; fall back to CPU (honestly labelled) when the
    chip pool is unavailable."""
    import jax

    try:
        devs = jax.devices()
        return jax.devices()[0].platform, devs
    except Exception as e:  # TPU pool unavailable — rerun on CPU
        print(f"[bench] default backend failed ({type(e).__name__}); CPU fallback", file=sys.stderr)
        from dist_svgd_tpu.utils.platform import force_cpu_backend

        force_cpu_backend()
        return "cpu", jax.devices()


def main():
    platform, _ = _init_platform()

    import jax
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import make_logreg_logp
    from dist_svgd_tpu.utils.datasets import load_benchmark

    fold = load_benchmark("banana", 42)
    logp = make_logreg_logp(fold.x_train, fold.t_train.reshape(-1))
    d = 1 + fold.x_train.shape[1]

    n_iters = N_ITERS if platform != "cpu" else 50  # CPU: measure less, same metric
    sampler = dt.Sampler(d, logp)

    # warmup with the *same* iteration count so the scan program is already
    # compiled (the compile cache is keyed by num_iter); timing measures
    # execution only
    sampler.run(N_PARTICLES, n_iters, 3e-3, seed=0, record=False)[0].block_until_ready()
    t0 = time.perf_counter()
    final, _ = sampler.run(N_PARTICLES, n_iters, 3e-3, seed=0, record=False)
    final.block_until_ready()
    wall = time.perf_counter() - t0

    updates_per_sec = N_PARTICLES * n_iters / wall

    # context: the reference's exact headline config (50 particles, 500 iters)
    sampler_small = dt.Sampler(d, logp)
    sampler_small.run(50, 500, 3e-3, seed=0, record=False)[0].block_until_ready()
    t0 = time.perf_counter()
    f2, _ = sampler_small.run(50, 500, 3e-3, seed=0, record=False)
    f2.block_until_ready()
    small_wall = time.perf_counter() - t0

    print(json.dumps({
        "metric": "particle_updates_per_sec (BayesLR banana, 10k particles)",
        "value": round(updates_per_sec, 1),
        "unit": "updates/sec",
        "vs_baseline": round(updates_per_sec / REFERENCE_BEST_UPDATES_PER_SEC, 2),
        "platform": platform,
        "n_particles": N_PARTICLES,
        "n_iters_measured": n_iters,
        "wall_s": round(wall, 3),
        "ref_headline_config_wall_s": round(small_wall, 3),
        "ref_headline_config_ref_wall_s": 2007.11,
    }))


if __name__ == "__main__":
    main()
