"""Rollout drill: measure progressive delivery end to end and emit ONE
BENCH-style ``canary_rollout`` JSON row.

Three legs over one warmed single-tenant :class:`ModelRegistry`
(logreg posterior, one pinned padding bucket so the steady windows are
compile-free by construction):

1. **shadow overhead, paired A/B** — arm a rollout whose plan can
   never leave the shadow stage (infinite hold), offer a near-identical
   candidate, then alternate ``--overhead-pairs`` (baseline, shadow)
   segment pairs: each pair replays the *identical* Poisson sub-trace
   twice back to back, first with the batcher's rollout hook disarmed
   (pure incumbent serving) and then re-armed LIVE (mirrors flowing).
   ``shadow_overhead_frac`` is the **median per-pair p99 ratio** — a
   one-sided phase comparison on the shared 2-core box mis-attributes
   host stalls (compile-burn tails, noisy neighbours) worth ~50 % of a
   millisecond-scale p99 to whichever phase they land on, in either
   direction; a transient hits one pair and the median shrugs it off,
   while a real critical-path cost shows up in every pair.  The client
   p99 must stay within ``--shadow-overhead-max`` (default 5 %) of
   baseline.
2. **good candidate** — offer a slightly-perturbed (in-divergence-
   budget) candidate under a fast staged plan and let the controller's
   own cadence walk it shadow → 2 % → 10 % → 50 % → 100 % → promotion,
   with live replay traffic feeding the generation-labelled SLO windows.
   The whole window runs under the retrace sentry with **zero** expected
   compiles: the candidate's bucket kernels compile at ``offer`` (off
   the request path, before the sentry opens), so any compile in the
   window is a retrace bug.  ``rollout_promote_s`` is the measured
   offer → promotion wall.
3. **bad candidate** — the same plan, but the offered ensemble passes
   through :class:`~dist_svgd_tpu.resilience.BadGenerationAt`
   (``saturate``: finite, admission-passing, prediction-garbage).  The
   shadow divergence window breaches and the controller rolls back by
   swapping to the still-resident incumbent: the drill pins **zero**
   checkpoint I/O (a counting wrapper over ``engine.reload`` — the only
   checkpoint-consuming seam in this stack), bitwise-unchanged incumbent
   predictions, and peak candidate exposure within
   ``--max-exposure`` (default 0.10: the bad generation must die before
   its canary split ever exceeds one configured stage).

Shadow-mirrored dispatches are classified separately throughout
(``workload_replay.mirror_counts`` — satellite accounting): they never
count as client ok/shed/error/lost, and the client accounting identity
``offered == completed + shed + errors + lost`` is checked per phase.

Unconditional FAILs (``row_ok``): the good candidate not reaching full
exposure and promotion, any lost or errored client request in any
phase, any steady-state recompile inside the sentried windows, the bad
candidate not rolling back (or exceeding the configured exposure
stage), any checkpoint read on the rollback path, a non-bitwise
incumbent after rollback, or shadow p99 overhead at/over the bound.

Usage::

    python tools/rollout_drill.py              # defaults fit the 2-core CI box
    python tools/rollout_drill.py --base-rps 120 --duration 10
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _percentile_ms(records):
    from dist_svgd_tpu.serving.batcher import _percentile

    lats = sorted(r["lat_ms"] for r in records if r["status"] == "ok")
    return (round(_percentile(lats, 0.50), 3),
            round(_percentile(lats, 0.99), 3))


def _median(vals):
    s = sorted(vals)
    mid = len(s) // 2
    return s[mid] if len(s) % 2 else 0.5 * (s[mid - 1] + s[mid])


def _client_counts(*wholes):
    """Sum the client-facing accounting over phase windows (mirrors are
    already excluded by ``window_metrics`` — they are batcher-internal
    work, not client traffic)."""
    out = {k: 0 for k in ("offered", "completed", "shed", "errors", "lost")}
    for w in wholes:
        for k in out:
            out[k] += w[k]
    return out


def _drive_until(reg, tenant, pool, predicate, *, timeout_s=30.0,
                 interval_s=0.02):
    """Keep a trickle of live requests flowing until ``predicate()`` is
    true (the controller's hold/min-request gates need traffic to judge)
    — returns ``(records, met)`` in replay-record shape."""
    records = []
    deadline = time.perf_counter() + timeout_s
    i = 0
    while not predicate():
        if time.perf_counter() > deadline:
            return records, False
        t0 = time.perf_counter()
        rec = {"t": 0.0, "rows": int(pool[i % len(pool)].shape[0]),
               "tenant": tenant}
        try:
            reg.submit(tenant, pool[i % len(pool)]).result(timeout=10.0)
            rec.update(status="ok",
                       lat_ms=(time.perf_counter() - t0) * 1e3)
        except Exception as e:  # pragma: no cover - box pathology
            rec.update(status="error", lat_ms=None,
                       error=f"{type(e).__name__}: {e}")
        records.append(rec)
        i += 1
        time.sleep(interval_s)
    return records, True


def run_drill(n_particles=256, dim=8, rows=8, base_rps=64.0, duration_s=8.0,
              good_duration_s=14.0, bad_duration_s=6.0, seed=0,
              shadow_fraction=0.25, max_divergence=0.05, p99_ms=150.0,
              max_exposure=0.10, shadow_overhead_max=0.05,
              control_interval_s=0.15, overhead_pairs=4):
    """Run all four phases; returns the ``canary_rollout`` row."""
    import jax

    import serve_bench
    from tools.jaxlint.sentry import retrace_sentry
    from workload_replay import (
        TraceConfig,
        generate_trace,
        make_submit,
        mirror_counts,
        replay,
        window_metrics,
    )

    from dist_svgd_tpu.resilience import BadGenerationAt
    from dist_svgd_tpu.rollout import RolloutPlan
    from dist_svgd_tpu.serving import ModelRegistry
    from dist_svgd_tpu.telemetry import MetricsRegistry

    tenant = "prod"
    metrics = MetricsRegistry()
    # ONE padding bucket (min == max == the fixed request size, batcher
    # max_batch == rows so coalescing can never grow a batch past it):
    # every batch of every generation lands in a bucket staged kernels
    # have already compiled — the structural zero-recompile precondition
    reg = ModelRegistry(metrics=metrics, max_total_buckets=8,
                        max_batch=rows, lanes=1, max_wait_ms=2.0,
                        max_queue_rows=4096)
    rng = np.random.default_rng(seed)
    parts = (0.05 * rng.normal(size=(n_particles, 1 + dim))
             ).astype(np.float32)
    reg.add_tenant(tenant, "logreg", particles=parts,
                   min_bucket=rows, max_bucket=rows)
    reg.warm()
    time.sleep(1.0)  # settle the warm's compile burn (cpu-shares box)

    eng = reg.tenant(tenant).engine
    pools = serve_bench.request_pool_by_size(dim, (rows,), per_size=32,
                                             seed=seed + 1)
    pool = pools[rows]
    submit = make_submit(reg.batcher, pools, model_registry=reg)
    # the fast staged plan both live phases run under
    plan = RolloutPlan(shadow_fraction=shadow_fraction,
                       shadow_min_mirrors=8, shadow_hold_s=0.5,
                       canary_stages=(0.02, 0.10, 0.50, 1.0),
                       stage_hold_s=0.4, stage_min_requests=4,
                       max_divergence=max_divergence, p99_ms=p99_ms,
                       breach_streak=2, seed=seed + 3)

    # -- leg 1: shadow overhead, paired A/B segments -------------------- #
    # A single baseline-then-shadow comparison is dominated by host drift
    # on the shared box (~ms-scale p99s, stalls worth 50% of one): so
    # alternate (baseline, shadow) segment pairs on the identical
    # sub-trace — the batcher's set_rollout(None/ro) live toggle is the
    # seam — and take the MEDIAN per-pair p99 ratio.  The candidate's
    # bucket kernels compile once at offer, outside every timed segment.
    hold_plan = RolloutPlan(shadow_fraction=shadow_fraction,
                            shadow_min_mirrors=10 ** 9,
                            shadow_hold_s=86400.0,
                            max_divergence=max_divergence, p99_ms=p99_ms,
                            seed=seed + 3)
    near = parts + np.float32(1e-3)
    ro = reg.begin_rollout(tenant, plan=hold_plan)
    ro.offer(near, tag="shadow_probe")
    pairs = max(2, int(overhead_pairs))
    seg_s = duration_s / pairs
    seg_wholes, pair_overheads = [], []
    base_p50s, base_p99s, shadow_p50s, shadow_p99s = [], [], [], []
    for i in range(pairs):
        seg_cfg = TraceConfig(duration_s=seg_s, base_rps=base_rps,
                              seed=seed + 2 + 31 * i, diurnal_amp=0.0,
                              rows_sizes=(rows,), rows_alpha=0.0,
                              tenants=(tenant,))
        events = generate_trace(seg_cfg)
        reg.batcher.set_rollout(None)   # disarm LIVE: pure incumbent
        rec_b = replay(events, submit)
        reg.batcher.set_rollout(ro)     # re-arm LIVE: mirrors flowing
        rec_s = replay(events, submit)
        seg_wholes.append(window_metrics(rec_b, 0.0, seg_s, p99_ms))
        seg_wholes.append(window_metrics(rec_s, 0.0, seg_s, p99_ms))
        b50, b99 = _percentile_ms(rec_b)
        s50, s99 = _percentile_ms(rec_s)
        base_p50s.append(b50)
        base_p99s.append(b99)
        shadow_p50s.append(s50)
        shadow_p99s.append(s99)
        if b99:
            pair_overheads.append(max(s99 / b99 - 1.0, 0.0))
    base_p50, base_p99 = _median(base_p50s), _median(base_p99s)
    shadow_p50, shadow_p99 = _median(shadow_p50s), _median(shadow_p99s)
    overhead = (round(_median(pair_overheads), 4)
                if pair_overheads else None)
    reg.end_rollout(tenant)  # drops the probe candidate, flushes mirrors
    shadow_mirrors = mirror_counts(metrics, tenant)

    # -- leg 2: good candidate — staged promote under the sentry -------- #
    gen_before = eng.stats()["generation_id"]
    cand_counter = metrics.counter("svgd_serve_requests_total",
                                   "requests fully resolved")
    cand_before = cand_counter.value(tenant=tenant, generation="candidate")
    good_cand = parts + (1e-3 * rng.normal(size=parts.shape)
                         ).astype(np.float32)
    ro = reg.begin_rollout(tenant, plan=plan)
    ro.offer(good_cand, tag="good", watermark=time.time())
    good_cfg = TraceConfig(duration_s=good_duration_s, base_rps=base_rps,
                           seed=seed + 4, diurnal_amp=0.0,
                           rows_sizes=(rows,), rows_alpha=0.0,
                           tenants=(tenant,))
    t_offer = time.perf_counter()
    with retrace_sentry("rollout good-candidate steady state") as sentry_g:
        ro.start(control_interval_s)
        records_good = replay(generate_trace(good_cfg), submit)
        tail_good, _ = _drive_until(reg, tenant, pool,
                                    lambda: not ro.active, timeout_s=30.0)
        ro.stop()
    good_wall = time.perf_counter() - t_offer
    st = ro.status()
    promote_rec = next((r for r in ro.log if r["event"] == "promote"), None)
    good_stages = [r["fraction"] for r in ro.log if r["event"] == "advance"]
    whole_good = window_metrics(records_good + tail_good, 0.0,
                                good_duration_s, p99_ms)
    good = {
        "promoted": bool(st["promotions"] == 1 and st["state"] == "idle"),
        "promote_s": (promote_rec or {}).get("promote_s"),
        "wall_s": round(good_wall, 3),
        "stages": good_stages,
        "candidate_requests": int(
            cand_counter.value(tenant=tenant, generation="candidate")
            - cand_before),
        "generation_before": gen_before,
        "generation_after": eng.stats()["generation_id"],
    }
    reg.end_rollout(tenant)

    # -- leg 3: bad candidate — breach, roll back, stay resident -------- #
    gen_serving = eng.stats()["generation_id"]
    probe = pool[0]
    inc_before = {k: np.array(v, copy=True)
                  for k, v in eng.predict(probe).items()}
    reload_calls = {"n": 0}
    orig_reload = eng.reload

    def counting_reload(*a, **k):  # the only checkpoint-consuming seam
        reload_calls["n"] += 1
        return orig_reload(*a, **k)

    eng.reload = counting_reload
    # saturate (huge finite weights) rather than scramble: this drill's
    # incumbent is a weakly-informative posterior, where sign-flipping
    # still predicts ~0.5 — saturation breaks the predictive variance no
    # matter how diffuse the incumbent is (measured divergence ~0.14)
    fault = BadGenerationAt(0, kind="saturate")
    bad_cand = fault.apply(parts) if fault.active(0) else parts
    ro = reg.begin_rollout(tenant, plan=plan)
    ro.offer(bad_cand, tag="bad")
    bad_cfg = TraceConfig(duration_s=bad_duration_s, base_rps=base_rps,
                          seed=seed + 5, diurnal_amp=0.0,
                          rows_sizes=(rows,), rows_alpha=0.0,
                          tenants=(tenant,))
    with retrace_sentry("rollout bad-candidate rollback") as sentry_b:
        ro.start(control_interval_s)
        records_bad = replay(generate_trace(bad_cfg), submit)
        tail_bad, _ = _drive_until(reg, tenant, pool,
                                   lambda: not ro.active, timeout_s=20.0)
        ro.stop()
    st2 = ro.status()
    rollback_rec = next((r for r in ro.log if r["event"] == "rollback"),
                        None)
    peak_fraction = max([r["fraction"] for r in ro.log
                         if r["event"] == "advance"], default=0.0)
    whole_bad = window_metrics(records_bad + tail_bad, 0.0,
                               bad_duration_s, p99_ms)
    inc_after = eng.predict(probe)
    del eng.reload  # restore the class method
    bitwise = (sorted(inc_before) == sorted(inc_after)
               and all(np.array_equal(inc_before[k], inc_after[k])
                       for k in inc_before))
    bad = {
        "rolled_back": bool(st2["rollbacks"] == 1 and st2["state"] == "idle"),
        "at_stage": (rollback_rec or {}).get("at_stage"),
        "objectives": (rollback_rec or {}).get("objectives"),
        "peak_fraction": peak_fraction,
        "max_exposure": max_exposure,
        "checkpoint_reloads": reload_calls["n"],
        "incumbent_bitwise": bool(bitwise),
        "serving_generation_unchanged": bool(
            eng.stats()["generation_id"] == gen_serving),
    }
    reg.end_rollout(tenant)
    mirrors_total = mirror_counts(metrics, tenant)
    client = _client_counts(*seg_wholes, whole_good, whole_bad)
    reg.close(drain=True)

    compiles = ((sentry_g.compiles + sentry_b.compiles)
                if sentry_g.supported else None)
    return {
        "metric": "canary_rollout",
        "unit": "seconds from candidate offer to full promotion",
        "platform": jax.devices()[0].platform,
        "n": n_particles, "dim": dim, "rows": rows,
        "base_rps": base_rps, "duration_s": duration_s,
        "good_duration_s": good_duration_s,
        "bad_duration_s": bad_duration_s,
        "plan": plan.describe(),
        "value": good["promote_s"],
        "rollout_promote_s": good["promote_s"],
        "shadow_overhead_frac": overhead,
        "shadow_overhead_max": shadow_overhead_max,
        "overhead_pairs": [round(o, 4) for o in pair_overheads],
        "baseline_p50_ms": base_p50, "baseline_p99_ms": base_p99,
        "shadow_p50_ms": shadow_p50, "shadow_p99_ms": shadow_p99,
        "shadow_mirrors": shadow_mirrors["mirrors"],
        "mirrors_total": mirrors_total["mirrors"],
        "mirror_dropped": mirrors_total["mirror_dropped"],
        "mirror_errors": mirrors_total["mirror_errors"],
        "good": good,
        "bad": bad,
        "client": client,
        "sentry_supported": sentry_g.supported,
        "sentry_compiles": compiles,
        "steady_state_recompiles": compiles,
    }


def row_ok(row):
    """The unconditional ``canary_rollout`` gates; returns ``(ok, why)``
    — every entry in ``why`` is a FAIL (``tools/perf_regress.py`` joins
    them)."""
    why = []
    good = row.get("good") or {}
    bad = row.get("bad") or {}
    client = row.get("client") or {}
    if not good.get("promoted"):
        why.append("good candidate never reached full exposure and "
                   f"promotion (stages seen: {good.get('stages')})")
    if client.get("lost"):
        why.append(f"{client['lost']} client request(s) lost — every "
                   "admitted request must resolve through offer, canary "
                   "and rollback")
    if client.get("errors"):
        why.append(f"{client['errors']} client request(s) errored during "
                   "the rollout phases")
    if row.get("steady_state_recompiles"):
        why.append(f"{row['steady_state_recompiles']} steady-state "
                   "compile(s) inside the sentried rollout windows — "
                   "staging is the only documented compile and it runs "
                   "before the window opens")
    if not bad.get("rolled_back"):
        why.append("bad candidate was never rolled back")
    if bad.get("peak_fraction", 0.0) > bad.get("max_exposure", 0.0):
        why.append(f"bad candidate reached {bad.get('peak_fraction')} "
                   f"exposure (> configured {bad.get('max_exposure')})")
    if bad.get("checkpoint_reloads"):
        why.append(f"rollback touched the checkpoint path "
                   f"({bad['checkpoint_reloads']} reload call(s)) — it "
                   "must swap to the resident incumbent in O(1)")
    if not bad.get("incumbent_bitwise"):
        why.append("incumbent predictions changed across the bad "
                   "candidate's lifecycle — rollback must be bitwise")
    if not bad.get("serving_generation_unchanged"):
        why.append("serving generation moved during the bad rollout — "
                   "the candidate must never be promoted")
    overhead = row.get("shadow_overhead_frac")
    if overhead is not None and overhead >= row.get("shadow_overhead_max",
                                                    0.05):
        why.append(f"shadow mirroring added {overhead:.1%} to client p99 "
                   f"(bound {row.get('shadow_overhead_max'):.0%}) — "
                   "mirrors must stay off the critical path")
    return (not why), why


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=256, help="particles")
    ap.add_argument("--dim", type=int, default=8, help="feature dim")
    ap.add_argument("--rows", type=int, default=8,
                    help="rows per request (= the single padding bucket)")
    ap.add_argument("--base-rps", type=float, default=64.0)
    ap.add_argument("--duration", type=float, default=8.0,
                    help="total trace seconds per side of the paired "
                         "baseline/shadow overhead phase")
    ap.add_argument("--overhead-pairs", type=int, default=4,
                    help="interleaved (baseline, shadow) segment pairs; "
                         "shadow_overhead_frac is the median pair ratio")
    ap.add_argument("--good-duration", type=float, default=14.0,
                    help="good-candidate phase trace seconds")
    ap.add_argument("--bad-duration", type=float, default=6.0,
                    help="bad-candidate phase trace seconds")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--shadow-fraction", type=float, default=0.25)
    ap.add_argument("--max-divergence", type=float, default=0.05)
    ap.add_argument("--p99-ms", type=float, default=150.0,
                    help="candidate latency SLO the canary is judged on")
    ap.add_argument("--max-exposure", type=float, default=0.10,
                    help="the bad candidate must roll back before its "
                         "split exceeds this configured stage")
    ap.add_argument("--shadow-overhead-max", type=float, default=0.05,
                    help="allowed client-p99 inflation while mirroring")
    args = ap.parse_args()

    row = run_drill(
        n_particles=args.n, dim=args.dim, rows=args.rows,
        base_rps=args.base_rps, duration_s=args.duration,
        good_duration_s=args.good_duration,
        bad_duration_s=args.bad_duration, seed=args.seed,
        shadow_fraction=args.shadow_fraction,
        max_divergence=args.max_divergence, p99_ms=args.p99_ms,
        max_exposure=args.max_exposure,
        shadow_overhead_max=args.shadow_overhead_max,
        overhead_pairs=args.overhead_pairs,
    )
    print(json.dumps(row), flush=True)
    ok, why = row_ok(row)
    if not ok:
        print(json.dumps({"metric": "canary_rollout", "ok": False,
                          "why": why}), file=sys.stderr, flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
