"""Isolate the single-chip vmap-emulation penalty of the sharded step.

Round-1 measurement (docs/notes.md): the vmap-emulated 8-shard
``all_particles`` config runs at ~3.7M up/s on the one real chip while the
unsharded step runs ~7M up/s — same total FLOPs (each lane scores all n
particles on 1/S of the data rows; the Gram work tiles to the same n² pairs).
This script times hand-built variants of the step to find where the factor
of ~2 goes.  Usage: ``python tools/profile_emulation.py [--iters 100]``.
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "experiments"))
from paths import DATA_DIR  # noqa: F401  (bootstraps sys.path)

import jax
import jax.numpy as jnp
from jax import lax

from dist_svgd_tpu.models.logreg import logreg_logp
from dist_svgd_tpu.ops.kernels import RBF
from dist_svgd_tpu.ops.pallas_svgd import phi_pallas, resolve_phi_fn
from dist_svgd_tpu.ops.svgd import phi
from dist_svgd_tpu.utils.datasets import load_benchmark
from dist_svgd_tpu.utils.rng import init_particles_per_shard

N = 10_000
S = 8


def timed_scan(step, particles, iters, reps=3):
    """Scan timing, bench.py protocol: warm (compile), then ``reps``
    state-chained runs (each feeds the previous output) under one trailing
    scalar fetch — ``block_until_ready`` through the tunnel is not a
    reliable fence, and a single rep is exposed to the ±40% pool variance
    this tool exists to control for."""

    @jax.jit
    def run(p):
        def body(parts, i):
            return step(parts, i), None

        out, _ = lax.scan(body, p, jnp.arange(iters))
        return out

    import numpy as np

    np.asarray(run(particles))  # warm/compile, full fetch
    t0 = time.perf_counter()
    out = particles
    for _ in range(reps):
        out = run(out)
    np.asarray(out)[0, 0]
    wall = (time.perf_counter() - t0) / reps
    return N * iters / wall, wall


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=100)
    args = ap.parse_args()

    fold = load_benchmark("banana", 42)
    x = jnp.asarray(fold.x_train)
    t = jnp.asarray(fold.t_train.reshape(-1))
    rows = (x.shape[0] // S) * S
    x, t = x[:rows], t[:rows]
    d = 1 + x.shape[1]
    rows_per = rows // S
    scale = float(S)  # N_global / N_local

    P0 = init_particles_per_shard(0, N, d, S)
    eps = jnp.float32(3e-3)
    kernel = RBF(1.0)
    phi_auto = resolve_phi_fn(kernel, "auto", S)  # DistSampler's emulation hint

    score_fn = jax.grad(logreg_logp, argnums=0)
    batched_score = jax.vmap(score_fn, in_axes=(0, None))

    # stacked per-lane data (S, rows_per, ...)
    xs_stack = x.reshape(S, rows_per, -1)
    ts_stack = t.reshape(S, rows_per)

    results = {}

    # A. unsharded global step (the 7M up/s reference point)
    def step_unsharded(P, i):
        scores = batched_score(P, (x, t))
        return P + eps * phi_auto(P, P, scores)

    results["A:unsharded"] = timed_scan(step_unsharded, P0, args.iters)
    print("A:unsharded", results["A:unsharded"], flush=True)

    # B. vmap-emulated all_particles (what DistSampler does today)
    def lane_step(block, lane_data):
        interacting = lax.all_gather(block, "sh", tiled=True)
        scores = scale * batched_score(interacting, lane_data)
        return block + eps * phi_auto(block, interacting, scores)

    vstep = jax.vmap(lane_step, in_axes=(0, 0), axis_name="sh", axis_size=S)

    def step_vmap(P, i):
        blocks = P.reshape(S, N // S, d)
        new = vstep(blocks, (xs_stack, ts_stack))
        return new.reshape(N, d)

    results["B:vmap_all_particles"] = timed_scan(step_vmap, P0, args.iters)
    print("B:vmap_all_particles", results["B:vmap_all_particles"], flush=True)

    # B2. same but force the XLA phi
    phi_xla = lambda y, xx, s: phi(y, xx, s, kernel)

    def lane_step_xla(block, lane_data):
        interacting = lax.all_gather(block, "sh", tiled=True)
        scores = scale * batched_score(interacting, lane_data)
        return block + eps * phi_xla(block, interacting, scores)

    vstep_xla = jax.vmap(lane_step_xla, in_axes=(0, 0), axis_name="sh", axis_size=S)

    def step_vmap_xla(P, i):
        return vstep_xla(P.reshape(S, N // S, d), (xs_stack, ts_stack)).reshape(N, d)

    results["B2:vmap_xla_phi"] = timed_scan(step_vmap_xla, P0, args.iters)
    print("B2:vmap_xla_phi", results["B2:vmap_xla_phi"], flush=True)

    # C. specialized emulation: stacked scores + ONE phi_pallas over rows with
    # per-lane score stacking folded into a single (n, d) xs per lane... not
    # expressible as one call; instead unroll S phi calls (no vmap).
    def step_unrolled(P, i):
        scores_stack = jax.vmap(lambda dl: scale * batched_score(P, dl))(
            (xs_stack, ts_stack)
        )  # (S, N, d)
        blocks = P.reshape(S, N // S, d)
        outs = [
            blocks[r] + eps * phi_auto(blocks[r], P, scores_stack[r])
            for r in range(S)
        ]
        return jnp.concatenate(outs, axis=0)

    results["C:unrolled_phi"] = timed_scan(step_unrolled, P0, args.iters)
    print("C:unrolled_phi", results["C:unrolled_phi"], flush=True)

    # D. vmap over lanes but scores computed once outside the vmap
    def lane_phi(block, lane_scores, P):
        return block + eps * phi_auto(block, P, lane_scores)

    vphi = jax.vmap(lane_phi, in_axes=(0, 0, None))

    def step_scores_outside(P, i):
        scores_stack = jax.vmap(lambda dl: scale * batched_score(P, dl))(
            (xs_stack, ts_stack)
        )
        blocks = P.reshape(S, N // S, d)
        return vphi(blocks, scores_stack, P).reshape(N, d)

    results["D:vmap_scores_outside"] = timed_scan(step_scores_outside, P0, args.iters)
    print("D:vmap_scores_outside", results["D:vmap_scores_outside"], flush=True)

    # E. all_scores emulation, specialized: psum == sum over lanes -> single
    # global phi (identical to unsharded but with lane-sliced score compute)
    def step_all_scores_special(P, i):
        scores = jnp.sum(
            jax.vmap(lambda dl: batched_score(P, dl))((xs_stack, ts_stack)), axis=0
        )
        return P + eps * phi_auto(P, P, scores)

    results["E:all_scores_special"] = timed_scan(step_all_scores_special, P0, args.iters)
    print("E:all_scores_special", results["E:all_scores_special"], flush=True)

    # F. vmap all_particles with the per-lane tile config pinned explicitly
    # (bk=256/bm=1024 is what _auto_block picks for k=1250 today — this row
    # deliberately duplicates B under current defaults, so a future
    # _auto_block change shows up as B diverging from F)
    def lane_step_p(block, lane_data):
        interacting = lax.all_gather(block, "sh", tiled=True)
        scores = scale * batched_score(interacting, lane_data)
        return block + eps * phi_pallas(block, interacting, scores,
                                        block_k=256, block_m=1024)

    vstep_p = jax.vmap(lane_step_p, in_axes=(0, 0), axis_name="sh", axis_size=S)

    def step_vmap_p(P, i):
        return vstep_p(P.reshape(S, N // S, d), (xs_stack, ts_stack)).reshape(N, d)

    results["F:vmap_pallas_bk256"] = timed_scan(step_vmap_p, P0, args.iters)
    print("F:vmap_pallas_bk256", results["F:vmap_pallas_bk256"], flush=True)

    print()
    for k, (ups, wall) in results.items():
        print(f"{k:28s} {ups/1e6:8.2f} M up/s   wall {wall:.3f}s")


if __name__ == "__main__":
    main()
