"""Program auditor gate: per-plan program cards + XP rule enforcement
(round 22).

``dist_svgd_tpu/analysis`` audits every compiled plan (jaxpr + lowered
StableHLO) into a **program card** — collective inventory with payload
bytes per mesh axis, donation-aliasing verification, dtype-promotion
sweep, peak live-intermediate estimate, and the materialized-n×n check.
This tool is the gate that makes those cards a *recorded artifact*: it
builds a deterministic suite of representative plans on the CPU box
(8 virtual devices, x64 on — the exact tier-1 environment), audits them,
and compares each card against the committed baseline in
``tools/program_cards.json``.

A run FAILs deterministically — no accelerator, no timing noise — when:

- any XP001–XP005 finding fires on a card (non-allowlisted; the
  allowlist path suffix is ``plan://<label>``);
- a card's per-kind **collective count** exceeds its baseline (a plan
  that suddenly all-gathers twice per step is a regression even when
  the numerics still pass);
- a baseline card had ``donation_ok`` and the current one does not, or
  its donation **marker count** dropped (the "donate_argnums set but
  aliasing silently dropped" failure mode);
- a card's materialized-n×n buffer count grew;
- a card present in the baseline was not produced, or a produced card
  has no baseline (run ``--record`` to bless a deliberate change).

``peak_live_bytes_est`` and ``largest_intermediate_bytes`` ride the card
for the record but do not gate — they are lowering-version-sensitive
estimates, not contracts.

Mirrors the ``tools/perf_regress.py`` conventions: ``--record`` refuses
to overwrite the baseline while any gate FAILs (``--force`` overrides),
and ``--list-missing`` audits the baseline file without building
anything — builders whose cards are absent are gates that silently
cannot fire.  Findings render through ``tools/jaxlint/report.py``
(``--format=text|json|github``), the same reporting path as the jaxlint
CLI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.jaxlint import allowlist as allowlist_mod
from tools.jaxlint import report

CARDS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "program_cards.json")

#: Gate-relevant card fields: a baseline entry must carry all of these
#: (``as_dict`` emits more — the extras ride for the record).
GATED_FIELDS = ("collectives", "donation_ok", "donation_markers",
                "nxn_buffers", "num_shards")


# ---------------------------------------------------------------------------
# builder suite
# ---------------------------------------------------------------------------
#
# Each builder constructs a representative training/serving object inside a
# scoped registry and runs exactly enough dispatches to capture first-call
# avals.  Shapes are tiny (n=24 particles, d=2; 64x8 serving ensembles) so
# the whole suite compiles in well under the tier-1 wall budget, and
# distinctive (24 is no bucket size and no feature dim) so the n×n scan
# cannot collide with an unrelated dimension.


def _quad_logp(theta, data=None):
    import jax.numpy as jnp

    return -0.5 * jnp.sum(theta ** 2)


def _build_sampler_exact():
    from dist_svgd_tpu.sampler import Sampler

    s = Sampler(2, _quad_logp)
    s.run(n=24, num_iter=3, step_size=0.1, seed=0)
    return s


def _build_sampler_rff():
    from dist_svgd_tpu.sampler import Sampler

    s = Sampler(2, _quad_logp, kernel_approx="rff", phi_impl="xla")
    s.run(n=24, num_iter=3, step_size=0.1, seed=0)
    return s


def _dist_particles():
    import numpy as np
    import jax.numpy as jnp

    return jnp.asarray(np.random.default_rng(0).normal(size=(16, 2)))


def _build_dist_gather():
    from dist_svgd_tpu.distsampler import DistSampler

    ds = DistSampler(2, _quad_logp, None, _dist_particles(),
                     include_wasserstein=False)
    ds.run_steps(3, 0.05)
    return ds


def _build_dist_w2_sinkhorn():
    from dist_svgd_tpu.distsampler import DistSampler

    ds = DistSampler(2, _quad_logp, None, _dist_particles(),
                     include_wasserstein=True,
                     wasserstein_solver="sinkhorn")
    ds.run_steps(3, 0.05)
    return ds


def _build_dist_rff():
    from dist_svgd_tpu.distsampler import DistSampler

    ds = DistSampler(2, _quad_logp, None, _dist_particles(),
                     include_wasserstein=False, kernel_approx="rff",
                     phi_impl="xla")
    ds.run_steps(3, 0.05)
    return ds


def _serve_particles():
    import numpy as np

    return np.random.default_rng(1).normal(size=(64, 8)).astype(np.float32)


def _build_serve_logreg():
    import numpy as np
    from dist_svgd_tpu.serving import PredictiveEngine

    eng = PredictiveEngine("logreg", _serve_particles(),
                           min_bucket=4, max_bucket=16)
    eng.warmup()
    eng.predict(np.random.default_rng(2).normal(size=(3, 7))
                .astype(np.float32))
    return eng


def _build_serve_bf16():
    from dist_svgd_tpu.serving import PredictiveEngine

    eng = PredictiveEngine("logreg", _serve_particles(), min_bucket=4,
                           max_bucket=8, dtype="bfloat16")
    eng.warmup()
    return eng


#: name -> builder, in print order.  The names are the ``--list-missing``
#: contract (mirroring ``perf_regress.WINDOWED_ROWS``): a name whose cards
#: are absent from the baseline file is a gate that silently cannot fire.
BUILDERS = (
    ("sampler_exact", _build_sampler_exact),
    ("sampler_rff", _build_sampler_rff),
    ("dist_gather", _build_dist_gather),
    ("dist_w2_sinkhorn", _build_dist_w2_sinkhorn),
    ("dist_rff", _build_dist_rff),
    ("serve_logreg", _build_serve_logreg),
    ("serve_bf16", _build_serve_bf16),
)
BUILDER_NAMES = tuple(name for name, _ in BUILDERS)


def setup_environment(device_count: int = 8) -> None:
    """Pin the audit to the tier-1 CPU environment (8 virtual devices,
    x64 on) so card signatures are reproducible across boxes.  Must run
    before the first JAX import; delegates to ``tests/_jax_env.py`` so
    the axon-plugin workaround stays in one place."""
    from tests._jax_env import setup_cpu

    setup_cpu(device_count, enable_x64=True)


def run_suite(names=None) -> Tuple[list, list]:
    """Build every requested suite entry in its own scoped registry and
    audit it.  Returns ``(cards, findings)`` with each card's ``builder``
    recorded in ``card.meta`` so the baseline knows which gate owns it."""
    from dist_svgd_tpu.analysis import audit_registry, use_registry

    selected = [(n, b) for n, b in BUILDERS if names is None or n in names]
    unknown = set(names or ()) - {n for n, _ in selected}
    if unknown:
        raise SystemExit(f"program_audit: unknown builder(s) {sorted(unknown)}; "
                         f"expected a subset of {list(BUILDER_NAMES)}")
    all_cards, all_findings = [], []
    for name, build in selected:
        with use_registry() as reg:
            # hold the builder's return value across the audit: the
            # registry weakrefs the compiled plans, so dropping the owning
            # sampler/engine before auditing would garbage-collect every
            # program the builder just compiled
            keepalive = build()
            cards, findings = audit_registry(reg)
            del keepalive
        for card in cards:
            card.meta["builder"] = name
        all_cards.extend(cards)
        all_findings.extend(findings)
    return all_cards, all_findings


# ---------------------------------------------------------------------------
# gate
# ---------------------------------------------------------------------------


def baseline_key(card) -> str:
    """Baseline identity: ``builder/label(signature)``.  The builder
    namespace matters — e.g. ``sampler_exact`` and ``sampler_rff`` lower
    the same label at the same avals (the φ choice is internal to the
    scanned body), so the raw card key alone would alias two different
    programs onto one baseline entry."""
    return f"{card.meta.get('builder', '?')}/{card.key}"


def load_baseline(path: str = CARDS_PATH) -> dict:
    if not os.path.exists(path):
        return {"cards": {}}
    with open(path) as fh:
        doc = json.load(fh)
    doc.setdefault("cards", {})
    return doc


def compare_card(cur: dict, base: dict) -> List[str]:
    """Regression reasons for one card vs its baseline (empty = PASS)."""
    reasons = []
    for kind in sorted(set(cur["collectives"]) | set(base["collectives"])):
        was, now = base["collectives"].get(kind, 0), cur["collectives"].get(kind, 0)
        if now > was:
            reasons.append(f"collective {kind} count {was} -> {now}")
    if base["donation_ok"] and not cur["donation_ok"]:
        reasons.append("donation aliasing dropped (donation_ok True -> False)")
    if cur["donation_markers"] < base["donation_markers"]:
        reasons.append(f"donation markers {base['donation_markers']} -> "
                       f"{cur['donation_markers']}")
    if cur["nxn_buffers"] > base["nxn_buffers"]:
        reasons.append(f"materialized nxn buffers {base['nxn_buffers']} -> "
                       f"{cur['nxn_buffers']}")
    if cur["num_shards"] != base["num_shards"]:
        reasons.append(f"num_shards {base['num_shards']} -> {cur['num_shards']}")
    return reasons


def gate(cards, findings, baseline: dict,
         builders=BUILDER_NAMES) -> Tuple[List[dict], List, bool]:
    """Judge the suite.  Returns ``(rows, kept_findings, ok)`` where each
    row is ``{"card", "status", "reasons"}`` — status ``PASS`` /
    ``FAIL`` / ``NO_BASELINE`` / ``MISSING`` — and ``kept_findings`` are
    the non-allowlisted XP findings (each one FAILs its card's row,
    naming the rule).  ``builders`` scopes the disappeared-card check: a
    ``--builders`` subset run must not flag the unbuilt suite entries'
    baseline cards as MISSING."""
    kept = [f for f in findings
            if not allowlist_mod.is_allowlisted(f.path, f.rule, f.line)]
    by_label: Dict[str, List] = {}
    for f in kept:
        by_label.setdefault(f.path[len("plan://"):], []).append(f)

    base_cards = baseline.get("cards", {})
    rows, seen = [], set()
    for card in cards:
        key = baseline_key(card)
        seen.add(key)
        # findings attach to a label; every card under that label FAILs
        # (one serving label covers multiple bucket cards — all implicated)
        reasons = [f"{f.rule}: {f.message}"
                   for f in by_label.get(card.label, [])]
        base = base_cards.get(key)
        if base is None:
            status = "FAIL" if reasons else "NO_BASELINE"
            if not reasons:
                reasons = ["no baseline card — run --record to bless"]
        else:
            reasons += compare_card(card.as_dict(), base)
            status = "FAIL" if reasons else "PASS"
        rows.append({"card": key, "status": status, "reasons": reasons})
    in_scope = {key for key, card in base_cards.items()
                if card.get("builder") in builders}
    for key in sorted(in_scope - seen):
        rows.append({"card": key, "status": "MISSING",
                     "reasons": ["baseline card not produced by the suite"]})
    ok = all(r["status"] == "PASS" for r in rows)
    return rows, kept, ok


def missing_builders(baseline: dict, expected=BUILDER_NAMES) -> List[str]:
    """Builders with NO card in the baseline file — their regression
    gates return NO_BASELINE every run, i.e. they silently cannot fire.
    Works without JAX: it only reads the committed artifact."""
    present = {card.get("builder") for card in baseline.get("cards", {}).values()}
    return [name for name in expected if name not in present]


def record(cards, path: str = CARDS_PATH) -> None:
    doc = {
        "_meta": {
            "tool": "python -m tools.program_audit --record",
            "environment": "cpu x8 virtual devices, x64 on (tests/_jax_env)",
            "gated_fields": list(GATED_FIELDS),
        },
        "cards": {},
    }
    for card in cards:
        d = card.as_dict()
        d["builder"] = card.meta.get("builder")
        doc["cards"][baseline_key(card)] = d
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.program_audit",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--builders", nargs="*", metavar="NAME",
                    help=f"suite subset to run (default: all of "
                         f"{' '.join(BUILDER_NAMES)})")
    ap.add_argument("--format", choices=report.FORMATS, default="text",
                    dest="fmt", help="finding/report format (default: text)")
    ap.add_argument("--record", action="store_true",
                    help="rewrite tools/program_cards.json from this run "
                         "(refused while any XP finding fires — see --force)")
    ap.add_argument("--force", action="store_true",
                    help="allow --record despite findings (blessing a "
                         "deliberate contract change)")
    ap.add_argument("--list-missing", action="store_true",
                    help="print the builders with no baseline card and exit "
                         "(reads the artifact only; needs no JAX)")
    ap.add_argument("--cards-path", default=CARDS_PATH,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    baseline = load_baseline(args.cards_path)

    if args.list_missing:
        # same contract as perf_regress --list-missing: audit the committed
        # artifact without touching an accelerator or compiling anything
        missing = missing_builders(baseline)
        print(json.dumps({
            "builders": len(BUILDER_NAMES),
            "missing": missing,
            # every gate here is unconditional: XP findings fire with or
            # without a baseline; only the regression deltas go dormant
            "gates": {name: "findings+regression" for name in missing},
        }))
        return 0

    setup_environment()
    cards, findings = run_suite(args.builders)
    rows, kept, ok = gate(cards, findings, baseline,
                          builders=tuple(args.builders)
                          if args.builders else BUILDER_NAMES)

    if args.fmt == "json":
        report.render(kept, "json",
                      rows=rows,
                      cards=[c.as_dict() for c in cards],
                      row={"row": "program_audit",
                           "status": "PASS" if ok else "FAIL",
                           "cards": len(cards), "findings": len(kept)})
    else:
        if args.fmt == "github" and kept:
            report.render(kept, "github")
        for row in rows:
            line = f"program_audit: {row['status']:<11} {row['card']}"
            if row["reasons"]:
                line += "  [" + "; ".join(row["reasons"]) + "]"
            print(line)
        if args.fmt == "text" and kept:
            report.render(kept, "text", stream=sys.stderr)
        print(json.dumps({"row": "program_audit",
                          "status": "PASS" if ok else "FAIL",
                          "cards": len(cards), "findings": len(kept)}))

    if args.record:
        if kept and not args.force:
            print("program_audit: refusing --record with live findings "
                  "(pass --force to bless deliberately)", file=sys.stderr)
            return 1
        record(cards, args.cards_path)
        print(json.dumps({"recorded_to": args.cards_path,
                          "cards": len(cards)}))
        return 0

    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
