"""Autotune + roofline measurement for the Pallas φ kernel (VERDICT r1 #5).

Three measurements at the north-star shape (k, m, d) = (10k, 10k, 3):

1. **Pure-exp roofline**: scan-chained elementwise ``exp`` throughput on the
   VPU (f32 and bf16) — the φ step evaluates k·m exps, so this bounds any
   implementation of the step.
2. **Block-size sweep**: ``phi_pallas`` over (block_k, block_m) pairs, vs the
   fused XLA φ, bench.py timing protocol (state-chained reps, scalar fetch).
3. **bf16-Gram variant**: φ with the Gram tile cast to bf16 before the MXU
   contraction — error budget vs the f64 numpy oracle and speed delta.

Usage: ``python tools/pallas_autotune.py [--iters 50]``; add ``--big-d``
for the covertype-shape big-d kernel table (tiles + bf16x3 + error budget).
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "experiments"))
from paths import DATA_DIR  # noqa: F401  (bootstraps sys.path)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dist_svgd_tpu.ops.kernels import RBF
from dist_svgd_tpu.ops.pallas_svgd import phi_pallas
from dist_svgd_tpu.ops.svgd import phi

K = M = 10_000
D = 3

# --big-d: the covertype per-lane φ shape (docs/notes.md big-d section)
BIG_K, BIG_M, BIG_D = 1250, 10_000, 55


def _make_run(fn, iters):
    """One jitted length-``iters`` chained scan of ``fn`` — the shared step
    wrapper of :func:`timed` and :func:`timed_group`."""

    @jax.jit
    def run(x):
        out, _ = lax.scan(lambda c, i: (fn(c), None), x, jnp.arange(iters))
        return out

    return run


def timed(fn, x0, iters, reps=3):
    """Chained scan timing with a trailing scalar fetch (bench.py protocol)."""
    run = _make_run(fn, iters)
    np.asarray(run(x0))
    t0 = time.perf_counter()
    out = x0
    for _ in range(reps):
        out = run(out)
    np.asarray(out).ravel()[0]
    return (time.perf_counter() - t0) / (reps * iters)


def timed_group(named_fns, x0, iters, samples=3):
    """Interleaved min-of-samples timing of several step functions.

    Two artifacts make naive A-then-B subtractions lie on the shared pool
    (docs/notes.md timing protocol): session drift (a no-exp ablation once
    printed a *negative* exp share that way), and an **idle-credit burst**
    — the first dispatch sequence after any pause runs ~35% faster than
    the sustained rate, so whichever variant is timed first wins for free.
    Interleave the variants, and inside each sample run each program once
    untimed immediately before its timed run, so every number is the
    sustained rate."""
    runs = []
    for name, fn in named_fns:
        run = _make_run(fn, iters)
        np.asarray(run(x0)).ravel()[0]  # compile, untimed
        runs.append((name, run))
    best = {name: float("inf") for name, _ in runs}
    for _ in range(samples):
        for name, run in runs:
            np.asarray(run(x0)).ravel()[0]  # saturate: burn the idle credit
            t0 = time.perf_counter()
            np.asarray(run(x0)).ravel()[0]
            best[name] = min(best[name], (time.perf_counter() - t0) / iters)
    return best


def exp_roofline(iters):
    """Elements/s of a bare chained exp on a (4096, 4096) tile."""
    n = 4096
    x = jnp.ones((n, n), jnp.float32)
    t_f32 = timed(lambda c: jnp.exp(-c), x, iters)
    t_bf16 = timed(lambda c: jnp.exp(-c), x.astype(jnp.bfloat16), iters)
    print(f"exp roofline f32 : {n*n/t_f32/1e9:8.2f} G exp/s  ({t_f32*1e3:.3f} ms / {n}x{n})")
    print(f"exp roofline bf16: {n*n/t_bf16/1e9:8.2f} G exp/s  ({t_bf16*1e3:.3f} ms / {n}x{n})")
    return n * n / t_f32


def sweep(y, x, s, iters):
    results = {}
    eps = jnp.float32(1e-6)

    def make(fn):
        # chain by feeding phi output back into the updated set
        return lambda c: c + eps * fn(c)

    t = timed(make(lambda c: phi(c, x, s, RBF(1.0))), y, iters)
    results["xla"] = t
    print(f"XLA fused φ                  : {t*1e3:7.3f} ms  ({K*M/t/1e9:6.1f} G pairs/s)", flush=True)

    for bk in (256, 512, 1024, 2048):
        for bm in (256, 512, 1024, 2048):
            try:
                t = timed(
                    make(lambda c, bk=bk, bm=bm: phi_pallas(c, x, s, block_k=bk, block_m=bm)),
                    y, iters,
                )
            except Exception as e:
                print(f"pallas bk={bk:4d} bm={bm:4d}: FAILED {type(e).__name__}", flush=True)
                continue
            results[(bk, bm)] = t
            print(f"pallas bk={bk:4d} bm={bm:4d}        : {t*1e3:7.3f} ms  ({K*M/t/1e9:6.1f} G pairs/s)", flush=True)
    return results


def _noexp_kernel(y_ref, xT_ref, xsT_ref, o_ref, acc_ref, ksum_ref, *,
                  d_true, m_true, nm, d2_cap):
    """The current small-d φ kernel (per-dim VPU broadcasts and drive,
    ops/pallas_svgd.py:_phi_kernel_small_d) with ``exp`` replaced by
    identity — same traffic and arithmetic otherwise (incl. the mask-free
    sentinel padding: without the exp the sentinel columns feed huge-but-
    finite garbage into the sums, which is timing-equivalent; a masked
    variant measured *slower than the full kernel* — the iota/compare/
    select cost exceeds the exp's, which is why the production kernel is
    sentinel-padded), so (T_full − T_noexp) isolates the VPU exp cost.
    Output values are garbage — timing only."""
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    y = y_ref[:]
    xT = xT_ref[:]
    xsT = xsT_ref[:]
    d2 = None
    for c in range(d_true):
        diff = y[:, c:c + 1] - xT[c:c + 1, :]
        d2 = diff * diff if d2 is None else d2 + diff * diff
    kt = -jnp.minimum(d2, d2_cap)  # exp elided (production clamp kept)
    cols = [
        jnp.sum(kt * xsT[c:c + 1, :], axis=1, keepdims=True)
        for c in range(d_true)
    ]
    pad = y.shape[1] - d_true
    contrib = jnp.concatenate(
        cols + [jnp.zeros((y.shape[0], pad), jnp.float32)], axis=1
    )
    rowsum = jnp.sum(kt, axis=1, keepdims=True)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        ksum_ref[:] = jnp.zeros_like(ksum_ref)

    acc_ref[:] = acc_ref[:] + contrib
    ksum_ref[:] = ksum_ref[:] + rowsum

    @pl.when(j == nm - 1)
    def _():
        o_ref[:] = (acc_ref[:] + 2.0 * y * ksum_ref[:, :1]) / m_true


def phi_noexp(y, x, s, bk, bm):
    """pallas_call wrapper around :func:`_noexp_kernel` at the φ blocking."""
    import functools

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from dist_svgd_tpu.ops.pallas_svgd import (
        _D2_CAP, _FAR, SMALL_D, _pad_to, _round_up,
    )

    k, d = y.shape
    m = x.shape[0]
    kp, mp = _round_up(k, bk), _round_up(m, bm)
    dp = 128
    f32 = jnp.float32
    yp = _pad_to(y.astype(f32), kp, dp)
    xsT = _pad_to((s.astype(f32) - 2.0 * x.astype(f32)).T, SMALL_D, mp)
    xT = _pad_to(x.T.astype(f32), SMALL_D, mp, value=_FAR)  # production sentinel
    nk, nm = kp // bk, mp // bm
    kern = functools.partial(_noexp_kernel, d_true=d, m_true=m, nm=nm,
                             d2_cap=_D2_CAP)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((kp, dp), f32),
        grid=(nk, nm),
        in_specs=[
            pl.BlockSpec((bk, dp), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((SMALL_D, bm), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((SMALL_D, bm), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bk, dp), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((bk, dp), f32), pltpu.VMEM((bk, 128), f32)],
    )(yp, xT, xsT)
    return out[:k, :d]


def f64_oracle_phi(y, x, s, h=1.0):
    """Loopless f64 numpy φ for error budgets."""
    y64, x64, s64 = (np.asarray(a, np.float64) for a in (y, x, s))
    d2 = ((y64[:, None, :] - x64[None, :, :]) ** 2).sum(-1)
    kt = np.exp(-d2 / h)
    drive = kt @ s64
    repulse = (2.0 / h) * (y64 * kt.sum(1)[:, None] - kt @ x64)
    return (drive + repulse) / x64.shape[0]


def big_d(iters):
    """Big-d kernel measurements at the covertype per-lane shape: tile A/B
    (256² round-1 default vs the 256×1024 asymmetric default) and the
    bf16x3 fast tier, incumbents timed first (docs/notes.md protocol), plus
    both error budgets vs the f64 oracle at a median-scale bandwidth."""
    rng = np.random.default_rng(0)
    y = jnp.asarray(rng.normal(size=(BIG_K, BIG_D)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(BIG_M, BIG_D)), jnp.float32)
    s = jnp.asarray(rng.normal(size=(BIG_M, BIG_D)), jnp.float32)
    h = float(2 * BIG_D)  # median-scale: h=1 underflows every kernel value
    eps = jnp.float32(1e-6)

    best = timed_group([
        ("f32 256x256 (round-1 default)",
         lambda c: c + eps * phi_pallas(c, x, s, bandwidth=h,
                                        block_k=256, block_m=256)),
        ("f32 256x1024 (current default)",
         lambda c: c + eps * phi_pallas(c, x, s, bandwidth=h)),
        ("bf16x3 default tiles",
         lambda c: c + eps * phi_pallas(c, x, s, bandwidth=h,
                                        gram_dtype=jnp.bfloat16)),
        ("XLA fused",
         lambda c: c + eps * phi(c, x, s, RBF(h))),
    ], y, iters)
    print(f"\nbig-d φ at ({BIG_K}, {BIG_M}, {BIG_D}), h={h}:")
    for name, t in best.items():
        print(f"  {name:32s} {t*1e3:7.3f} ms  "
              f"({BIG_K*BIG_M/t/1e9:6.1f} G pairs/s)", flush=True)

    sub = 200  # the full (1250, 10000, 55) f64 broadcast is ~5 GB transient
    want = f64_oracle_phi(y[:sub], x, s, h=h)
    scale = np.abs(want).max()
    for name, gd in [("f32", None), ("bf16x3", jnp.bfloat16)]:
        got = np.asarray(phi_pallas(y[:sub], x, s, bandwidth=h, gram_dtype=gd))
        print(f"  max |φ_{name} − φ_f64| / max|φ| : "
              f"{np.abs(got - want).max()/scale:.2e}", flush=True)


def harvest():
    """Per-regime block-size sweep over the shape ladder the framework
    actually runs (round-5, VERDICT r04 item 8): 8-shard lane shapes
    (n/8, n) at n = 10k and 100k, the unsharded 10k and 100k squares, and
    the big-d covertype lane.  Prints the per-shape winner table to encode
    into ``ops/pallas_svgd.py:_MEASURED_BLOCKS`` (which ``phi_pallas``
    consults before the padding heuristic), interleaved-timed per shape so
    pool drift cannot crown the wrong tile."""
    rng = np.random.default_rng(0)
    shapes = [
        (1_250, 10_000, 3),     # 8-shard lane, north star
        (10_000, 10_000, 3),    # unsharded 10k square
        (12_500, 100_000, 3),   # 8-shard lane at n=100k
        (100_000, 100_000, 3),  # unsharded 100k square
        (1_250, 10_000, 55),    # big-d covertype lane
    ]
    eps = jnp.float32(1e-6)
    winners = {}
    for k, m, d in shapes:
        y = jnp.asarray(rng.normal(size=(k, d)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(m, d)), jnp.float32)
        s = jnp.asarray(rng.normal(size=(m, d)), jnp.float32)
        h = 1.0 if d <= 8 else float(2 * d)  # big-d: median-scale bandwidth
        # size the chain so one timed scan is ~0.5-2 s of φ work
        iters = int(max(3, min(50, 6e9 / (k * m))))
        named = []
        for bk in (256, 512, 1024):
            for bm in (256, 512, 1024):
                def fn(c, bk=bk, bm=bm):
                    return c + eps * phi_pallas(c, x, s, bandwidth=h,
                                                block_k=bk, block_m=bm)
                try:  # probe-compile: VMEM-overflow combos drop out here
                    # an autotune sweep compiles once per tile combo by design
                    np.asarray(jax.jit(fn)(y)).ravel()[0]  # jaxlint: disable=JL001
                except Exception as e:
                    print(f"  ({k},{m},{d}) {bk}x{bm}: FAILED "
                          f"{type(e).__name__}", flush=True)
                    continue
                named.append((f"{bk}x{bm}", fn))
        best = timed_group(named, y, iters)
        for name in sorted(best, key=best.get):
            t = best[name]
            print(f"  ({k},{m},{d}) {name:9s} {t*1e3:8.3f} ms "
                  f"({k*m/t/1e9:6.1f} G pairs/s)", flush=True)
        win = min(best, key=best.get)
        winners[(k, m, d)] = (win, best[win])
        print(f"shape ({k},{m},{d}): best {win}", flush=True)
    print("\n== table for ops/pallas_svgd.py:_MEASURED_BLOCKS ==")
    for (k, m, d), (win, t) in winners.items():
        bk, bm = (int(v) for v in win.split("x"))
        print(f"    ({d <= 8}, {k}, {m}): ({bk}, {bm}),"
              f"  # {t*1e3:.3f} ms measured")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--skip-sweep", action="store_true")
    ap.add_argument("--big-d", action="store_true",
                    help="measure the big-d (covertype-shape) kernel instead "
                         "of the small-d north star")
    ap.add_argument("--harvest", action="store_true",
                    help="sweep the per-regime shape ladder and print the "
                         "_MEASURED_BLOCKS table (module docstring)")
    args = ap.parse_args()

    if args.harvest:
        harvest()
        return
    if args.big_d:
        big_d(args.iters)
        return

    rng = np.random.default_rng(0)
    y = jnp.asarray(rng.normal(size=(K, D)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(M, D)), jnp.float32)
    s = jnp.asarray(rng.normal(size=(M, D)), jnp.float32)

    if not args.skip_sweep:
        exp_roofline(args.iters)
        results = sweep(y, x, s, args.iters)
        best = min(results, key=results.get)
        print(f"\nbest: {best}  {results[best]*1e3:.3f} ms  "
              f"(XLA/best ratio {results['xla']/results[best]:.2f}x)")

    eps = jnp.float32(1e-6)
    bk = bm = 1024
    best = timed_group([
        ("full", lambda c: c + eps * phi_pallas(c, x, s, block_k=bk, block_m=bm)),
        # clip: the exp-free output contains huge sentinel garbage, and
        # feeding it back unclipped drives the chain into inf/NaN slow
        # paths that dominate the timing
        ("noexp", lambda c: c + eps * jnp.clip(phi_noexp(c, x, s, bk, bm), -1.0, 1.0)),
        ("bf16", lambda c: c + eps * phi_pallas(c, x, s, block_k=bk, block_m=bm,
                                                gram_dtype=jnp.bfloat16)),
    ], y, args.iters)
    t_full, t_noexp, t_bf16 = best["full"], best["noexp"], best["bf16"]
    print()
    print(f"φ full f32  (1024²): {t_full*1e3:7.3f} ms  ({K*M/t_full/1e9:6.1f} G pairs/s)")
    print(f"φ no-exp    (1024²): {t_noexp*1e3:7.3f} ms  → exp share ≈ "
          f"{(t_full-t_noexp)/t_full*100:.0f}% of the step")
    print(f"φ bf16-gram (1024²): {t_bf16*1e3:7.3f} ms  ({K*M/t_bf16/1e9:6.1f} G pairs/s, "
          f"{t_full/t_bf16:.2f}x vs f32)")

    # error budget vs the f64 oracle (on a subsample: the full 10k oracle is
    # an (10k,10k,3) broadcast in numpy — slow but fine once)
    sub = 2000
    want = f64_oracle_phi(y[:sub], x, s)
    got_f32 = np.asarray(phi_pallas(y[:sub], x, s, block_k=bk, block_m=bm))
    got_bf16 = np.asarray(
        phi_pallas(y[:sub], x, s, block_k=bk, block_m=bm, gram_dtype=jnp.bfloat16)
    )
    scale = np.abs(want).max()
    print(f"max |φ_f32  − φ_f64| / max|φ| : {np.abs(got_f32 - want).max()/scale:.2e}")
    print(f"max |φ_bf16 − φ_f64| / max|φ| : {np.abs(got_bf16 - want).max()/scale:.2e}")


if __name__ == "__main__":
    main()
