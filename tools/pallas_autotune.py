"""Autotune + roofline measurement for the Pallas φ kernel (VERDICT r1 #5).

Three measurements at the north-star shape (k, m, d) = (10k, 10k, 3):

1. **Pure-exp roofline**: scan-chained elementwise ``exp`` throughput on the
   VPU (f32 and bf16) — the φ step evaluates k·m exps, so this bounds any
   implementation of the step.
2. **Block-size sweep**: ``phi_pallas`` over (block_k, block_m) pairs, vs the
   fused XLA φ, bench.py timing protocol (state-chained reps, scalar fetch).
3. **bf16-Gram variant**: φ with the Gram tile cast to bf16 before the MXU
   contraction — error budget vs the f64 numpy oracle and speed delta.

Usage: ``python tools/pallas_autotune.py [--iters 50]``.
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "experiments"))
from paths import DATA_DIR  # noqa: F401  (bootstraps sys.path)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dist_svgd_tpu.ops.kernels import RBF
from dist_svgd_tpu.ops.pallas_svgd import phi_pallas
from dist_svgd_tpu.ops.svgd import phi

K = M = 10_000
D = 3


def timed(fn, x0, iters, reps=3):
    """Chained scan timing with a trailing scalar fetch (bench.py protocol)."""

    @jax.jit
    def run(x):
        def body(c, i):
            return fn(c), None

        out, _ = lax.scan(body, x, jnp.arange(iters))
        return out

    np.asarray(run(x0))
    t0 = time.perf_counter()
    out = x0
    for _ in range(reps):
        out = run(out)
    np.asarray(out).ravel()[0]
    return (time.perf_counter() - t0) / (reps * iters)


def exp_roofline(iters):
    """Elements/s of a bare chained exp on a (4096, 4096) tile."""
    n = 4096
    x = jnp.ones((n, n), jnp.float32)
    t_f32 = timed(lambda c: jnp.exp(-c), x, iters)
    t_bf16 = timed(lambda c: jnp.exp(-c), x.astype(jnp.bfloat16), iters)
    print(f"exp roofline f32 : {n*n/t_f32/1e9:8.2f} G exp/s  ({t_f32*1e3:.3f} ms / {n}x{n})")
    print(f"exp roofline bf16: {n*n/t_bf16/1e9:8.2f} G exp/s  ({t_bf16*1e3:.3f} ms / {n}x{n})")
    return n * n / t_f32


def sweep(y, x, s, iters):
    results = {}
    eps = jnp.float32(1e-6)

    def make(fn):
        # chain by feeding phi output back into the updated set
        return lambda c: c + eps * fn(c)

    t = timed(make(lambda c: phi(c, x, s, RBF(1.0))), y, iters)
    results["xla"] = t
    print(f"XLA fused φ                  : {t*1e3:7.3f} ms  ({K*M/t/1e9:6.1f} G pairs/s)", flush=True)

    for bk in (256, 512, 1024, 2048):
        for bm in (256, 512, 1024, 2048):
            try:
                t = timed(
                    make(lambda c, bk=bk, bm=bm: phi_pallas(c, x, s, block_k=bk, block_m=bm)),
                    y, iters,
                )
            except Exception as e:
                print(f"pallas bk={bk:4d} bm={bm:4d}: FAILED {type(e).__name__}", flush=True)
                continue
            results[(bk, bm)] = t
            print(f"pallas bk={bk:4d} bm={bm:4d}        : {t*1e3:7.3f} ms  ({K*M/t/1e9:6.1f} G pairs/s)", flush=True)
    return results


def _noexp_kernel(y_ref, xT_ref, xs_ref, o_ref, acc_ref, ksum_ref, *,
                  d_true, block_m, m_true, nm):
    """The small-d φ kernel with ``exp`` replaced by identity — identical
    memory traffic, broadcasts, mask, and MXU contractions, so
    (T_full − T_noexp) isolates the VPU exp cost."""
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    y = y_ref[:]
    xT = xT_ref[:]
    xs = xs_ref[:]
    d2 = None
    for c in range(d_true):
        diff = y[:, c:c + 1] - xT[c:c + 1, :]
        d2 = diff * diff if d2 is None else d2 + diff * diff
    kt = -d2  # exp elided
    col = jax.lax.broadcasted_iota(jnp.int32, kt.shape, dimension=1)
    kt = jnp.where(col + j * block_m < m_true, kt, 0.0)
    contrib = jnp.dot(kt, xs, preferred_element_type=jnp.float32,
                      precision=jax.lax.Precision.HIGHEST)
    rowsum = jnp.sum(kt, axis=1, keepdims=True)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        ksum_ref[:] = jnp.zeros_like(ksum_ref)

    acc_ref[:] = acc_ref[:] + contrib
    ksum_ref[:] = ksum_ref[:] + rowsum

    @pl.when(j == nm - 1)
    def _():
        o_ref[:] = (acc_ref[:] + 2.0 * y * ksum_ref[:, :1]) / m_true


def phi_noexp(y, x, s, bk, bm):
    """pallas_call wrapper around :func:`_noexp_kernel` at the φ blocking."""
    import functools

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from dist_svgd_tpu.ops.pallas_svgd import SMALL_D, _pad_to, _round_up

    k, d = y.shape
    m = x.shape[0]
    kp, mp = _round_up(k, bk), _round_up(m, bm)
    dp = 128
    f32 = jnp.float32
    yp = _pad_to(y.astype(f32), kp, dp)
    xs = _pad_to(s.astype(f32) - 2.0 * x.astype(f32), mp, dp)
    xT = _pad_to(x.T.astype(f32), SMALL_D, mp)
    nk, nm = kp // bk, mp // bm
    kern = functools.partial(_noexp_kernel, d_true=d, block_m=bm, m_true=m, nm=nm)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((kp, dp), f32),
        grid=(nk, nm),
        in_specs=[
            pl.BlockSpec((bk, dp), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((SMALL_D, bm), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, dp), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bk, dp), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((bk, dp), f32), pltpu.VMEM((bk, 128), f32)],
    )(yp, xT, xs)
    return out[:k, :d]


def f64_oracle_phi(y, x, s):
    """Loopless f64 numpy φ for error budgets."""
    y64, x64, s64 = (np.asarray(a, np.float64) for a in (y, x, s))
    d2 = ((y64[:, None, :] - x64[None, :, :]) ** 2).sum(-1)
    kt = np.exp(-d2)
    drive = kt @ s64
    repulse = 2.0 * (y64 * kt.sum(1)[:, None] - kt @ x64)
    return (drive + repulse) / x64.shape[0]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--skip-sweep", action="store_true")
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    y = jnp.asarray(rng.normal(size=(K, D)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(M, D)), jnp.float32)
    s = jnp.asarray(rng.normal(size=(M, D)), jnp.float32)

    if not args.skip_sweep:
        exp_roofline(args.iters)
        results = sweep(y, x, s, args.iters)
        best = min(results, key=results.get)
        print(f"\nbest: {best}  {results[best]*1e3:.3f} ms  "
              f"(XLA/best ratio {results['xla']/results[best]:.2f}x)")

    eps = jnp.float32(1e-6)
    bk = bm = 1024
    t_full = timed(lambda c: c + eps * phi_pallas(c, x, s, block_k=bk, block_m=bm),
                   y, args.iters)
    t_noexp = timed(lambda c: c + eps * phi_noexp(c, x, s, bk, bm), y, args.iters)
    t_bf16 = timed(
        lambda c: c + eps * phi_pallas(c, x, s, block_k=bk, block_m=bm,
                                       gram_dtype=jnp.bfloat16),
        y, args.iters,
    )
    print()
    print(f"φ full f32  (1024²): {t_full*1e3:7.3f} ms  ({K*M/t_full/1e9:6.1f} G pairs/s)")
    print(f"φ no-exp    (1024²): {t_noexp*1e3:7.3f} ms  → exp share ≈ "
          f"{(t_full-t_noexp)/t_full*100:.0f}% of the step")
    print(f"φ bf16-gram (1024²): {t_bf16*1e3:7.3f} ms  ({K*M/t_bf16/1e9:6.1f} G pairs/s, "
          f"{t_full/t_bf16:.2f}x vs f32)")

    # error budget vs the f64 oracle (on a subsample: the full 10k oracle is
    # an (10k,10k,3) broadcast in numpy — slow but fine once)
    sub = 2000
    want = f64_oracle_phi(y[:sub], x, s)
    got_f32 = np.asarray(phi_pallas(y[:sub], x, s, block_k=bk, block_m=bm))
    got_bf16 = np.asarray(
        phi_pallas(y[:sub], x, s, block_k=bk, block_m=bm, gram_dtype=jnp.bfloat16)
    )
    scale = np.abs(want).max()
    print(f"max |φ_f32  − φ_f64| / max|φ| : {np.abs(got_f32 - want).max()/scale:.2e}")
    print(f"max |φ_bf16 − φ_f64| / max|φ| : {np.abs(got_bf16 - want).max()/scale:.2e}")


if __name__ == "__main__":
    main()
