"""Measurement & validation tools (see tools/README.md).

This package marker exists so `python -m tools.jaxlint` resolves from the
repo root; the individual scripts keep their path-insertion prologues and
still run as plain `python tools/<script>.py`.
"""
