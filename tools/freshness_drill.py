"""Freshness drill: measure streaming SVGD end to end and emit ONE
BENCH-style ``freshness`` JSON row.

Two phases, each a full ingest → train → checkpoint → hot-reload loop
over the :mod:`dist_svgd_tpu.streaming` stack (logistic-regression
posterior on a synthetic drifting stream):

1. **bitwise** — a manual-clock replay: run A streams ``2k`` segments
   uninterrupted; run B streams ``k``, dies (every in-memory object
   dropped), and a cold process resumes from the checkpoint root on the
   same clock timeline for ``k`` more.  Final particles AND the stream
   cursor must be **bitwise identical** — the supervisor's resume
   exactness extended to continuously-arriving data.
2. **measured** — a real-clock run: batches become due every
   ``period_s`` on ``time.perf_counter``'s timeline, the drill paces one
   segment per arriving batch, and every segment publishes through
   ``CheckpointHotReloader`` to a live ``PredictiveEngine``.  The
   warm-up segments train to (near) convergence while recording the
   healthy posterior's pre-train check KSD; the drift guard is then
   armed at ``ksd_factor ×`` the recent maximum of that series
   (calibrate-then-arm — a fixed a-priori threshold would be wrong on
   every new model/box pair), a ``DriftAt`` **label-flip** is injected a
   few ordinals ahead (a full concept inversion: a covariate mean shift
   actually makes logreg *easier* — far from the boundary the likelihood
   flattens and the stale posterior looks fine), and the steady-state
   window runs under the retrace sentry.  The row's
   ``freshness_p50_s``/``p99_s`` are the measured event-time →
   first-serve latencies; drift must be detected and escalated to a
   re-fit within ``drift_window`` segments of the drifted ordinal's
   ingest.

Zero-compile accounting: each admitted hot reload rebuilds its bucket
kernels over the new ensemble — the *documented* off-request-path
compile (``PredictiveEngine.reload``).  The sentry therefore expects
exactly ``reloads × compiled_buckets`` compiles in the window;
``steady_state_recompiles`` is the excess, and the gate FAILs on any —
a retrace in the training scan (data swap), the drift diagnostics, the
checkpoint path, or the serve path.

Unconditional FAILs (``row_ok``): lost stream batches, a non-bitwise
kill→resume, drift served without retraining, any steady-state
recompile, or a breached streaming SLO.

Usage::

    python tools/freshness_drill.py            # defaults fit the 2-core CI box
    python tools/freshness_drill.py --steady-segments 30 --period 0.05
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class ManualClock:
    """Injectable clock for the bitwise phase: time moves only when the
    drill says so, so 'hours' of stream replay in milliseconds."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _build_stack(root, clock, registry, *, dim, batch_rows, corpus_rows,
                 batch_size, n_particles, steps_per_segment, refit_steps,
                 step_size, seed, period_s, start_time, faults=(),
                 buffer_capacity=64, drift_diag=None, reloader=None):
    """One fresh streaming stack (source → buffer → ring → sampler →
    supervisor) on a shared clock timeline and checkpoint root."""
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import make_logreg_split
    from dist_svgd_tpu.streaming import (
        GrowingCorpusStream,
        RowRing,
        StreamBuffer,
        StreamingSupervisor,
    )

    source = GrowingCorpusStream(
        batch_rows=batch_rows, dim=dim, seed=seed, period_s=period_s,
        start_time=start_time, faults=faults)
    buffer = StreamBuffer(source, buffer_capacity, registry=registry,
                          clock=clock)
    ring = RowRing(corpus_rows, dim)
    likelihood, prior = make_logreg_split()
    # zero-filled corpus placeholder: segment 1 ingests before it trains,
    # so the sampler never actually steps on this array — it only pins
    # the (capacity, dim) spec the compiled scan keeps forever
    sampler = dt.Sampler(
        dim + 1, likelihood, kernel=dt.RBF(1.0),
        data=(np.zeros((corpus_rows, dim), np.float32),
              np.ones((corpus_rows,), np.float64)),
        batch_size=batch_size, log_prior=prior)
    sup = StreamingSupervisor(
        sampler, step_size, buffer=buffer, ring=ring,
        steps_per_segment=steps_per_segment, refit_steps=refit_steps,
        drift_diagnostics=drift_diag, reloader=reloader,
        checkpoint_dir=root, checkpoint_every=steps_per_segment,
        segment_steps=steps_per_segment, n=n_particles, seed=seed,
        registry=registry, clock=clock, sleep=lambda s: None)
    return source, buffer, ring, sampler, sup


def bitwise_kill_resume(root, *, segments_each_side=2, **cfg):
    """Phase 1: uninterrupted vs killed-and-cold-resumed streaming runs on
    identical manual-clock timelines must end bitwise equal."""
    from dist_svgd_tpu.telemetry import MetricsRegistry

    total = 2 * segments_each_side
    period = cfg["period_s"]

    # -- run A: one process, `total` segments -------------------------- #
    clock_a = ManualClock()
    reg_a = MetricsRegistry()
    _, buf_a, _, _, sup_a = _build_stack(
        os.path.join(root, "bw_a"), clock_a, reg_a, start_time=0.0, **cfg)
    for _ in range(total):
        clock_a.advance(period)
        sup_a.run_segment_once()

    # -- run B: killed after half, cold-resumed on the same timeline ---- #
    clock_b = ManualClock()
    reg_b = MetricsRegistry()
    root_b = os.path.join(root, "bw_b")
    _, _, _, _, sup_b = _build_stack(
        root_b, clock_b, reg_b, start_time=0.0, **cfg)
    for _ in range(segments_each_side):
        clock_b.advance(period)
        sup_b.run_segment_once()
    t_kill = clock_b.t
    del sup_b  # the kill: every in-memory object is gone

    clock_b2 = ManualClock(t_kill)  # wall time keeps flowing
    reg_b2 = MetricsRegistry()
    _, buf_b2, _, _, sup_b2 = _build_stack(
        root_b, clock_b2, reg_b2, start_time=0.0, **cfg)
    for i in range(segments_each_side):
        clock_b2.advance(period)
        sup_b2.run_segment_once(resume=(i == 0))

    bitwise = bool(np.array_equal(np.asarray(sup_a.particles),
                                  np.asarray(sup_b2.particles)))
    return {
        "bitwise": bitwise and sup_a.t == sup_b2.t
        and buf_a.next_ordinal == buf_b2.next_ordinal,
        "segments": total,
        "t": sup_a.t,
        "stream_ordinals": buf_a.next_ordinal,
        "dropped": buf_a.dropped + buf_b2.dropped,
    }


def measured_stream(root, *, steady_segments, warmup_segments, ksd_factor,
                    drift_after, drift_magnitude, drift_window, max_lag_s,
                    probe_rows, **cfg):
    """Phase 2: the real-clock measured run (see module docstring)."""
    import jax

    from dist_svgd_tpu.resilience import DriftAt, GuardConfig
    from dist_svgd_tpu.serving.engine import (
        CheckpointHotReloader,
        PredictiveEngine,
    )
    from dist_svgd_tpu.telemetry import MetricsRegistry
    from dist_svgd_tpu.telemetry.diagnostics import (
        DiagnosticsConfig,
        PosteriorDiagnostics,
    )
    from dist_svgd_tpu.telemetry.slo import default_streaming_slos
    from dist_svgd_tpu.utils.rng import as_key, init_particles
    from tools.jaxlint.sentry import retrace_sentry

    registry = MetricsRegistry()
    clock = time.perf_counter
    period = cfg["period_s"]
    dim = cfg["dim"]
    ckpt_root = os.path.join(root, "measured")

    # serving side first: the engine cold-starts on the same initial
    # ensemble the supervisor will draw (same seed through the same
    # init_particles path), one 8-wide padding bucket, warmed now so the
    # steady window's serve path is compile-free
    parts0 = np.asarray(init_particles(
        as_key(cfg["seed"]), cfg["n_particles"], dim + 1))
    engine = PredictiveEngine("logreg", parts0, min_bucket=probe_rows,
                              max_bucket=probe_rows, registry=registry)
    engine.warmup()
    reloader = CheckpointHotReloader(engine, ckpt_root, key="particles")

    diag = PosteriorDiagnostics(
        DiagnosticsConfig(every_steps=1, row_chunk=256, max_points=256),
        registry=registry)

    source, buffer, _, _, sup = _build_stack(
        ckpt_root, clock, registry, start_time=clock() + period,
        drift_diag=diag, reloader=reloader, **cfg)
    x_probe = np.zeros((probe_rows, dim), np.float32)

    def wait_for_batch(timeout_s=30.0):
        deadline = clock() + timeout_s
        while not source.due(buffer.next_ordinal, clock()):
            if clock() > deadline:  # pragma: no cover - pathological box
                raise TimeoutError("stream stalled: no batch became due")
            time.sleep(period / 20)

    # -- warm-up: segment 1 compiles the scan; the never-trip guard makes
    # every later segment run (and, on segment 2, compile) the drift
    # check, whose pre-train KSD series is the calibration baseline ----- #
    sup.drift_guard = GuardConfig(max_ksd=float("inf"))
    g_ksd = registry.gauge("svgd_diag_ksd")
    base_ksds = []
    for _ in range(warmup_segments):
        wait_for_batch()
        sup.run_segment_once()
        engine.predict(x_probe)
        if g_ksd.has():
            base_ksds.append(float(g_ksd.value()))

    # -- calibrate-then-arm: threshold = factor × the recent max of the
    # healthy posterior's own pre-train check KSD (early-training KSD
    # still climbs, so only the tail of the series is trusted) ---------- #
    ksd_baseline = max(base_ksds[-4:]) if base_ksds else float(
        diag.compute(np.asarray(sup.particles), num_shards=1,
                     step=sup.t)["ksd"])
    ksd_threshold = ksd_baseline * ksd_factor
    sup.drift_guard = GuardConfig(max_ksd=ksd_threshold)
    # inject concept drift a few ordinals ahead — every batch from
    # `drift_ordinal` on has `drift_magnitude` of its labels flipped
    # (deterministic per ordinal; mutating faults mid-run only affects
    # ordinals not yet pulled)
    drift_ordinal = buffer.next_ordinal + drift_after
    source.faults = (DriftAt(drift_ordinal, kind="label_flip",
                             magnitude=drift_magnitude),)

    # -- steady-state window under the retrace sentry ------------------- #
    buckets = engine.stats()["bucket_cache_size"]
    segments = []
    reloads = 0
    drift_seg = None
    drift_ingest_seg = None
    drift_detect_s = None
    t_win0 = clock()
    with retrace_sentry("freshness steady state") as sentry:
        for i in range(steady_segments):
            wait_for_batch()
            seg = sup.run_segment_once()
            engine.predict(x_probe)  # serve the freshly-reloaded ensemble
            segments.append(seg)
            if seg["reload_step"] is not None:
                reloads += 1
            if drift_ingest_seg is None and buffer.next_ordinal > drift_ordinal:
                drift_ingest_seg = i
            if drift_seg is None and seg["drift"]:
                drift_seg = i
                drift_detect_s = clock() - source.event_time(drift_ordinal)
    wall_s = clock() - t_win0

    # the documented per-generation kernel rebuild is the ONLY compile
    # the window may contain; anything beyond it is a retrace bug
    expected_compiles = reloads * buckets
    recompiles = (sentry.compiles - expected_compiles
                  if sentry.supported else None)

    freshness = [s["freshness_s"] for s in segments
                 if s["freshness_s"] is not None]
    refits = sum(1 for s in segments if s["refit"])
    detect_segments = (None if drift_seg is None or drift_ingest_seg is None
                       else drift_seg - drift_ingest_seg)
    slo_doc = default_streaming_slos(
        registry, max_lag_s=max_lag_s, drop_budget=0.0).evaluate()

    return {
        "platform": jax.devices()[0].platform,
        "segments": len(segments),
        "wall_s": round(wall_s, 3),
        "freshness_p50_s": (round(float(np.percentile(freshness, 50)), 4)
                            if freshness else None),
        "freshness_p99_s": (round(float(np.percentile(freshness, 99)), 4)
                            if freshness else None),
        "freshness_count": len(freshness),
        "reloads": reloads,
        "reload_rejections": sum(1 for s in segments if s["reload_rejected"]),
        "reload_wall_ms_hist": registry.histogram(
            "svgd_engine_reload_wall_s").summary(scale=1e3),
        "drift_ordinal": drift_ordinal,
        "ksd_baseline": round(ksd_baseline, 4),
        "ksd_threshold": round(ksd_threshold, 4),
        "drift_detected": drift_seg is not None,
        "drift_detect_segments": detect_segments,
        "drift_detect_latency_s": (round(drift_detect_s, 3)
                                   if drift_detect_s is not None else None),
        "drift_retrained": bool(refits >= 1 and detect_segments is not None
                                and detect_segments <= drift_window),
        "refits": refits,
        "dropped": buffer.dropped,
        "rows_ingested": int(registry.counter(
            "svgd_stream_rows_total").value()),
        "sentry_supported": sentry.supported,
        "sentry_compiles": sentry.compiles if sentry.supported else None,
        "expected_reload_compiles": expected_compiles,
        "steady_state_recompiles": recompiles,
        "slo_status": slo_doc["status"],
        "slo": {name: {"status": o["status"], "burn_rate": o["burn_rate"]}
                for name, o in slo_doc["objectives"].items()},
    }


def run_drill(n_particles=256, dim=5, batch_rows=128, corpus_rows=512,
              batch_size=64, steps_per_segment=16, refit_factor=4,
              step_size=0.05, seed=0, period_s=0.08, steady_segments=18,
              warmup_segments=8, ksd_factor=2.0, drift_after=3,
              drift_magnitude=1.0, drift_window=6, max_lag_s=30.0,
              root=None):
    """Run both phases; returns the ``freshness`` row."""
    if root is None:
        import tempfile

        root = tempfile.mkdtemp(prefix="freshness_drill_")
    cfg = dict(dim=dim, batch_rows=batch_rows, corpus_rows=corpus_rows,
               batch_size=batch_size, n_particles=n_particles,
               steps_per_segment=steps_per_segment,
               refit_steps=refit_factor * steps_per_segment,
               step_size=step_size, seed=seed, period_s=period_s)

    bw = bitwise_kill_resume(root, segments_each_side=2, **cfg)
    measured = measured_stream(
        root, steady_segments=steady_segments,
        warmup_segments=warmup_segments, ksd_factor=ksd_factor,
        drift_after=drift_after, drift_magnitude=drift_magnitude,
        drift_window=drift_window, max_lag_s=max_lag_s, probe_rows=8,
        **cfg)

    row = {
        "metric": "freshness",
        "n": n_particles,
        "dim": dim,
        "batch_rows": batch_rows,
        "corpus_rows": corpus_rows,
        "batch_size": batch_size,
        "steps_per_segment": steps_per_segment,
        "refit_steps": refit_factor * steps_per_segment,
        "period_s": period_s,
        "resumed_bitwise_identical": bw["bitwise"],
        "bitwise_segments": bw["segments"],
        "dropped_total": bw["dropped"] + measured["dropped"],
    }
    row.update(measured)
    return row


def row_ok(row):
    """The unconditional freshness gates; returns ``(ok, why)`` — every
    entry in ``why`` is a FAIL (``tools/perf_regress.py`` joins them)."""
    why = []
    if row.get("dropped_total", 0):
        why.append(f"lost {row['dropped_total']} stream batch(es) — "
                   "buffer overflow dropped data")
    if not row.get("resumed_bitwise_identical"):
        why.append("mid-stream kill->resume was not bitwise identical")
    if not row.get("drift_detected"):
        why.append("injected drift never tripped the guard")
    elif not row.get("drift_retrained"):
        why.append("drift breach served without a timely re-fit "
                   f"(detected after {row.get('drift_detect_segments')} "
                   "segments)")
    if row.get("steady_state_recompiles"):
        why.append(f"{row['steady_state_recompiles']} steady-state "
                   "recompile(s) beyond the documented reload rebuilds")
    if row.get("slo_status") != "ok":
        why.append(f"streaming SLOs: {row.get('slo_status')} "
                   f"({row.get('slo')})")
    if row.get("freshness_p99_s") is None:
        why.append("no freshness observations — nothing was ever served")
    return (not why), why


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=256, help="particles")
    ap.add_argument("--dim", type=int, default=5, help="feature dim")
    ap.add_argument("--batch-rows", type=int, default=128)
    ap.add_argument("--corpus-rows", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=64,
                    help="minibatch rows per SVGD step")
    ap.add_argument("--steps-per-segment", type=int, default=16)
    ap.add_argument("--refit-factor", type=int, default=4,
                    help="re-fit steps as a multiple of steps_per_segment")
    ap.add_argument("--stepsize", type=float, default=0.05)
    ap.add_argument("--period", type=float, default=0.08,
                    help="event-time batch spacing, seconds")
    ap.add_argument("--steady-segments", type=int, default=18)
    ap.add_argument("--warmup-segments", type=int, default=8,
                    help="untimed segments training + calibrating the "
                         "drift baseline before the steady window")
    ap.add_argument("--ksd-factor", type=float, default=2.0,
                    help="drift threshold over the calibrated baseline KSD")
    ap.add_argument("--drift-after", type=int, default=3,
                    help="ordinals between arming and the injected drift")
    ap.add_argument("--drift-magnitude", type=float, default=1.0,
                    help="flipped-label fraction of the injected drift")
    ap.add_argument("--drift-window", type=int, default=6,
                    help="segments within which drift must be detected")
    ap.add_argument("--max-lag-s", type=float, default=30.0,
                    help="freshness SLO threshold for the row's slo_status")
    ap.add_argument("--root", default=None,
                    help="checkpoint scratch root (default: a temp dir)")
    args = ap.parse_args()

    row = run_drill(
        n_particles=args.n, dim=args.dim, batch_rows=args.batch_rows,
        corpus_rows=args.corpus_rows, batch_size=args.batch_size,
        steps_per_segment=args.steps_per_segment,
        refit_factor=args.refit_factor, step_size=args.stepsize,
        period_s=args.period, steady_segments=args.steady_segments,
        warmup_segments=args.warmup_segments,
        ksd_factor=args.ksd_factor, drift_after=args.drift_after,
        drift_magnitude=args.drift_magnitude,
        drift_window=args.drift_window, max_lag_s=args.max_lag_s,
        root=args.root,
    )
    print(json.dumps(row), flush=True)
    ok, why = row_ok(row)
    if not ok:
        print(json.dumps({"metric": "freshness", "ok": False, "why": why}),
              file=sys.stderr, flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
