"""Cross-host training drill: multi-process DCN mesh, host-sharded
checkpoints, kill-one-host elastic resume — ONE BENCH-style
``multihost_train`` JSON row.

Training so far lived in one process on one host; this drill makes the
multi-process axis real end to end and measures it.  Two modes, following
``tools/fleet_drill.py``'s fake/real split:

- **fake** (tier-1, any platform): everything runs in-process on the
  granule-major particle mesh.  The multi-process *topology* is exercised
  through its seams — per-process block checkpoints emulated with
  ``utils/checkpoint.py:split_state_for_processes``, reassembled with
  ``assemble_full_state``, the kill-one-host resume routed through
  ``reshard_state`` to the W−1 federation's shard count, and the
  coordinator loop driven with scripted
  :class:`~dist_svgd_tpu.resilience.federation.FakeWorker` handles — so
  every correctness gate (bitwise resume, RNG layout-freeness, steps lost,
  zero steady-state recompiles) runs without a real rendezvous;
- **real** (jax ≥ 0.5 CPU federations, or TPU hosts): W worker processes
  (``tools/multihost_worker.py``) rendezvous via ``multihost.initialize``,
  train through genuinely cross-process ``lax.ppermute`` hops, save
  host-sharded checkpoints, and the drill SIGKILLs one worker mid-run —
  :class:`~dist_svgd_tpu.resilience.federation.FederationSupervisor`
  detects the loss, drains the survivors, and relaunches at W−1 with
  ``--resume``.  On the jax<0.5 CPU-backend multiprocess gap the drill
  refuses up front with the one-line reason
  (``multihost.multiprocess_gap``) instead of dying mid-run in XLA.

The row reports updates/s for the gather and ring arms, ring-hop wall,
DCN-boundary crossings per hop (``multihost.dcn_boundary_crossings`` —
exactly the granule count on a granule-major mesh), and the elastic
numbers; ``perf_regress`` gates it (lost steps, divergent resume, or
post-restart steady-state recompiles = unconditional FAIL; the walls get
median+MAD windows).

Usage::

    python tools/multihost_train.py                  # fake mode
    python tools/multihost_train.py --mode real --processes 4 --devcount 2
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_TOL = 1e-4


def build_sampler(n, num_shards, mesh, *, exchange_impl="gather",
                  include_w2=False, kernel_approx=None, seed=0):
    """The drill's sampler: GMM posterior, gathered particles with local
    scores (the shard-count-invariant mode ``tools/elastic_drill.py``
    pins), on an explicit granule-major mesh."""
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.gmm import gmm_logp
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    parts = init_particles_per_shard(seed, n, 2, num_shards)
    return dt.DistSampler(
        num_shards, lambda th, _: gmm_logp(th), None, parts,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=include_w2,
        wasserstein_solver="sinkhorn" if include_w2 else "lp",
        sinkhorn_iters=20,
        exchange_impl=exchange_impl, mesh=mesh,
        kernel_approx=kernel_approx,
    )


def _timed_updates_per_s(ds, steps, step_size, n):
    """Particle-updates/s over ``steps`` warmed steps (one untimed warm
    call first, so compile never lands in the window)."""
    import jax

    ds.run_steps(1, step_size)
    jax.block_until_ready(ds.particles)
    w0 = time.perf_counter()
    ds.run_steps(steps, step_size)
    jax.block_until_ready(ds.particles)
    wall = time.perf_counter() - w0
    return n * steps / max(wall, 1e-9), wall / steps


def _fake_federation_report():
    """Drive the coordinator loop itself through a scripted kill-one
    lifecycle: generation 0 loses worker 1 (SIGKILL-shaped rc −9), the
    relaunched W−1 generation finishes clean."""
    from dist_svgd_tpu.resilience import FakeWorker, FederationSupervisor
    from dist_svgd_tpu.telemetry import MetricsRegistry

    def launcher(width, attempt):
        if attempt == 0:
            return [
                FakeWorker(f"rank{i}",
                           [None, None, -9 if i == 1 else None, None, 0])
                for i in range(width)
            ]
        return [FakeWorker(f"rank{i}", [None, 0]) for i in range(width)]

    sup = FederationSupervisor(
        launcher, processes=4, restart_budget=1,
        registry=MetricsRegistry(),
        clock=time.perf_counter, sleep=lambda s: None,
    )
    report = sup.run()
    return {
        "restarts": report["restarts"],
        "final_processes": report["processes"],
        "transitions": [
            {k: v for k, v in t.items() if k != "lost"}
            for t in report["transitions"]
        ],
    }


def run_drill(mode="auto", processes=4, devcount=2, n=288, num_steps=24,
              checkpoint_every=8, kill_step=None, step_size=0.05,
              timed_steps=8, tol=DEFAULT_TOL, root=None, seed=0):
    """Run the drill; returns the ``multihost_train`` row."""
    from dist_svgd_tpu.parallel import multihost

    if mode == "auto":
        mode = "fake" if multihost.multiprocess_gap(processes) else "real"
    if mode == "real":
        gap = multihost.multiprocess_gap(processes)
        if gap is not None:
            # the clean-refusal satellite: name the version up front instead
            # of XLA's mid-run CPU-backend failure
            return {"metric": "multihost_train", "mode": "real",
                    "status": "unsupported", "unsupported_reason": gap}
        return _run_real(processes=processes, devcount=devcount, n=n,
                         num_steps=num_steps,
                         checkpoint_every=checkpoint_every,
                         step_size=step_size, tol=tol, root=root, seed=seed)
    if mode != "fake":
        raise ValueError(f"unknown mode {mode!r}")
    return _run_fake(processes=processes, devcount=devcount, n=n,
                     num_steps=num_steps, checkpoint_every=checkpoint_every,
                     kill_step=kill_step, step_size=step_size,
                     timed_steps=timed_steps, tol=tol, root=root, seed=seed)


def _run_fake(*, processes, devcount, n, num_steps, checkpoint_every,
              kill_step, step_size, timed_steps, tol, root, seed):
    import jax
    import numpy as np

    from dist_svgd_tpu.ops.approx import KernelApprox
    from dist_svgd_tpu.parallel import multihost
    from dist_svgd_tpu.parallel.exchange import ring_hops_per_step
    from dist_svgd_tpu.utils import checkpoint as ckpt
    from tools.jaxlint.sentry import retrace_sentry

    if root is None:
        import tempfile

        root = tempfile.mkdtemp(prefix="multihost_train_")
    shards = processes * devcount
    if len(jax.devices()) < shards:
        return {"metric": "multihost_train", "mode": "fake",
                "status": "unsupported",
                "unsupported_reason":
                    f"need {shards} devices for the {processes}x{devcount} "
                    f"layout, have {len(jax.devices())}"}
    shards_after = (processes - 1) * devcount
    if n % shards or n % shards_after:
        raise ValueError(
            f"n ({n}) must divide both the W ({shards}) and W-1 "
            f"({shards_after}) shard counts"
        )
    if kill_step is None:
        # strictly between two checkpoints: the resume must replay steps
        kill_step = 2 * checkpoint_every + max(1, checkpoint_every // 2)
    if not checkpoint_every < kill_step < num_steps:
        raise ValueError(
            f"kill_step ({kill_step}) must land inside "
            f"({checkpoint_every}, {num_steps})"
        )
    ckpt_before_kill = (kill_step // checkpoint_every) * checkpoint_every
    mesh = multihost.make_particle_mesh(shards)

    # -------- arms: gather + ring perf, W2 / kernel-approx legs -------- #
    gather_ups, gather_step_wall = _timed_updates_per_s(
        build_sampler(n, shards, mesh, seed=seed), timed_steps, step_size, n)
    ring_ups, ring_step_wall = _timed_updates_per_s(
        build_sampler(n, shards, mesh, exchange_impl="ring", seed=seed),
        timed_steps, step_size, n)
    hops = ring_hops_per_step("all_particles", shards)
    variants_ok = True
    for kw in ({"include_w2": True},
               {"kernel_approx": KernelApprox("rff", num_features=64),
                "exchange_impl": "ring"}):
        v = build_sampler(n, shards, mesh, seed=seed, **kw)
        v.run_steps(2, step_size)
        variants_ok = variants_ok and bool(
            np.isfinite(np.asarray(v.particles)).all())

    # -------- multi-process-topology resume: bitwise vs uninterrupted -- #
    base = build_sampler(n, shards, mesh, seed=seed)
    base.run_steps(num_steps, step_size)
    final_base = np.asarray(base.particles)

    saver = build_sampler(n, shards, mesh, seed=seed)
    saver.run_steps(ckpt_before_kill, step_size)
    state = saver.state_dict()
    blocks = ckpt.split_state_for_processes(state, processes)
    paths = []
    for r, blk in enumerate(blocks):
        paths.append(ckpt.save_state(
            os.path.join(root, f"step_{ckpt_before_kill}", f"rank_{r}"),
            blk))
    # a lone foreign-layout block must be rejected, not half-restored
    single_block_rejected = False
    try:
        probe = build_sampler(n, shards, mesh, seed=seed)
        probe.load_state_dict(ckpt.load_state(paths[0]))
    except ValueError:
        # either shape-mismatch ("!= sampler") or foreign-layout
        # ("matches neither") — both are the refusal we require
        single_block_rejected = True
    assembled = ckpt.assemble_full_state(paths)
    resumed = build_sampler(n, shards, mesh, seed=seed)
    resumed.load_state_dict(assembled)
    resumed.run_steps(num_steps - ckpt_before_kill, step_size)
    resume_bitwise = bool(np.array_equal(
        np.asarray(resumed.particles), final_base))
    rng_layout_free = bool(np.array_equal(
        resumed.state_dict()["rng_batch_key"],
        base.state_dict()["rng_batch_key"]))
    man = ckpt.read_manifest(blocks[0])
    manifest_stamped = bool(
        man is not None and man["process_count"] == processes
        and man["granule_shards"].tolist() == [devcount] * processes)

    # -------- kill-one-worker: resume at W−1 on the same step grid ----- #
    # the federation died at kill_step; the survivors assemble the last
    # complete per-process save and reshard it to the W−1 shard count
    t_kill_detect = time.perf_counter()
    resharded = ckpt.reshard_state(assembled, shards_after)
    mesh_after = multihost.make_particle_mesh(shards_after)
    survivor = build_sampler(n, shards_after, mesh_after, seed=seed)
    survivor.load_state_dict(resharded)
    resumed_from = survivor.t
    steps_lost = kill_step - resumed_from
    # split the remaining grid in two equal segments: the first compiles
    # the W−1 program, the second re-runs it under the retrace sentry —
    # steady state after the restart must compile NOTHING
    remaining = num_steps - resumed_from
    seg = remaining // 2
    survivor.run_steps(seg, step_size)
    with retrace_sentry("post-restart steady state") as sentry:
        survivor.run_steps(remaining - seg, step_size)
    jax.block_until_ready(survivor.particles)
    killone_recovery_wall_s = time.perf_counter() - t_kill_detect
    killone_max_dev = float(
        np.abs(np.asarray(survivor.particles) - final_base).max())

    fed = _fake_federation_report()

    row = {
        "metric": "multihost_train",
        "mode": "fake",
        "status": "ok",
        "unsupported_reason": None,
        "platform": jax.devices()[0].platform,
        "processes": processes,
        "devcount": devcount,
        "shards": shards,
        "shards_after_loss": shards_after,
        "n": n,
        "num_steps": num_steps,
        "checkpoint_every": checkpoint_every,
        "updates_per_s_gather": round(gather_ups, 1),
        "updates_per_s_ring": round(ring_ups, 1),
        "updates_per_s_multi": None,  # real mode only: the W-process arm
        "gather_step_wall_ms": round(gather_step_wall * 1e3, 3),
        "ring_step_wall_ms": round(ring_step_wall * 1e3, 3),
        "ring_hops_per_step": hops["hops"],
        "ring_hop_wall_ms": round(
            ring_step_wall * 1e3 / max(hops["hops"], 1), 4),
        "dcn_crossings_per_hop": multihost.dcn_boundary_crossings(mesh),
        "variants_ok": bool(variants_ok),
        "manifest_stamped": manifest_stamped,
        "single_block_rejected": bool(single_block_rejected),
        "resume_bitwise": resume_bitwise,
        "rng_layout_free": rng_layout_free,
        "kill_step": kill_step,
        "resumed_from": int(resumed_from),
        "steps_lost": int(steps_lost),
        "expected_steps_lost": kill_step - ckpt_before_kill,
        "killone_to_shards": shards_after,
        "killone_max_dev": killone_max_dev,
        "killone_within_tol": bool(killone_max_dev <= tol),
        "killone_recovery_wall_s": round(killone_recovery_wall_s, 4),
        "post_restart_recompiles": sentry.compiles,
        "sentry_supported": sentry.supported,
        "federation_restarts": fed["restarts"],
        "federation_final_processes": fed["final_processes"],
        "federation_transitions": fed["transitions"],
    }
    return row


def _free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _KillTrigger:
    """Real-mode kill-one seam: wraps a worker handle and delivers a real
    SIGKILL the first time the federation's first full per-process save
    exists on disk (so the resumed W−1 generation has something to
    assemble) — the poll-side trigger keeps
    :class:`FederationSupervisor` itself unmodified."""

    def __init__(self, inner, root: str, step: int, nprocs: int):
        self._inner = inner
        self._root = root
        self._step = int(step)
        self._nprocs = int(nprocs)
        self.name = inner.name
        self.triggered = False

    def _save_complete(self) -> bool:
        d = os.path.join(self._root, f"step_{self._step}")
        return all(
            os.path.isdir(os.path.join(d, f"rank_{r}"))
            for r in range(self._nprocs)
        )

    def poll(self):
        if not self.triggered and self._save_complete():
            self.triggered = True
            self._inner.kill()  # real SIGKILL on the Popen
        return self._inner.poll()

    def kill(self):
        self._inner.kill()

    def wait(self, timeout_s: float = 30.0):
        return self._inner.wait(timeout_s)


def _run_real(*, processes, devcount, n, num_steps, checkpoint_every,
              step_size, tol, root, seed):
    import numpy as np

    from dist_svgd_tpu.resilience import (
        FederationSupervisor,
        SubprocessWorker,
    )
    from dist_svgd_tpu.telemetry import MetricsRegistry

    if root is None:
        import tempfile

        root = tempfile.mkdtemp(prefix="multihost_train_real_")
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "multihost_worker.py")
    logs = []

    def launcher(width, attempt):
        coord = f"127.0.0.1:{_free_port()}"
        handles = []
        for r in range(width):
            cmd = [sys.executable, worker,
                   "--rank", str(r), "--nprocs", str(width),
                   "--coordinator", coord, "--root", root,
                   "--devcount", str(devcount), "--n", str(n),
                   "--steps", str(num_steps),
                   "--checkpoint-every", str(checkpoint_every),
                   "--step-size", str(step_size), "--seed", str(seed)]
            if attempt > 0:
                cmd.append("--resume")
            log = open(os.path.join(root, f"gen{attempt}_rank{r}.log"), "w")
            logs.append(log)
            handles.append(SubprocessWorker(
                f"rank{r}",
                subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT),
            ))
        if attempt == 0:
            handles[1] = _KillTrigger(handles[1], root,
                                      step=checkpoint_every, nprocs=width)
        return handles

    sup = FederationSupervisor(
        launcher, processes=processes, restart_budget=1,
        poll_interval_s=0.2, registry=MetricsRegistry(),
    )
    t0 = time.perf_counter()
    try:
        report = sup.run()
    finally:
        for log in logs:
            log.close()
    # the surviving federation's own numbers
    done = []
    for r in range(report["processes"]):
        with open(os.path.join(root, f"done_rank{r}.json")) as fh:
            done.append(json.load(fh))
    rows = [np.load(os.path.join(root, f"final_rows_{r}.npy"))
            for r in range(report["processes"])]
    final_multi = np.concatenate(
        [r for _, r in sorted(
            ((d["row_start"], rows[i]) for i, d in enumerate(done)),
            key=lambda p: p[0])]
    )
    # single-process arm at the same global shape, uninterrupted
    from dist_svgd_tpu.parallel import multihost

    shards = processes * devcount
    mesh = multihost.make_particle_mesh(shards)
    import jax

    single = build_sampler(n, shards, mesh, seed=seed)
    ups_single, _ = _timed_updates_per_s(single, checkpoint_every,
                                         step_size, n)
    base = build_sampler(n, shards, mesh, seed=seed)
    base.run_steps(num_steps, step_size)
    jax.block_until_ready(base.particles)
    max_dev = float(np.abs(np.asarray(base.particles) - final_multi).max())
    walls = [d["step_wall_s"] for d in done if d["step_wall_s"]]
    return {
        "metric": "multihost_train",
        "mode": "real",
        "status": "ok",
        "unsupported_reason": None,
        "platform": jax.devices()[0].platform,
        "processes": processes,
        "devcount": devcount,
        "shards": shards,
        "shards_after_loss": (processes - 1) * devcount,
        "n": n,
        "num_steps": num_steps,
        "checkpoint_every": checkpoint_every,
        "updates_per_s_gather": round(ups_single, 1),
        "updates_per_s_multi": (
            round(n / float(np.median(walls)), 1) if walls else None),
        "dcn_crossings_per_hop": (
            done[0]["dcn_crossings_per_hop"] if done else None),
        "resume_t_complete": all(d["t"] == num_steps for d in done),
        "killone_max_dev": max_dev,
        "killone_within_tol": bool(max_dev <= tol),
        "federation_restarts": report["restarts"],
        "federation_final_processes": report["processes"],
        "federation_transitions": [
            {k: v for k, v in t.items() if k != "lost"}
            for t in report["transitions"]
        ],
        "drill_wall_s": round(time.perf_counter() - t0, 2),
    }


def row_ok(row):
    """``(ok, reasons)``: the drill's own acceptance — the unconditional
    gates ``perf_regress`` fails on.  An honest up-front refusal
    (``status='unsupported'``) is OK=True with its reason recorded: the
    platform cannot run the drill, and saying so cleanly is the contract."""
    if row.get("status") == "unsupported":
        return True, [f"unsupported: {row.get('unsupported_reason')}"]
    reasons = []
    if row.get("mode") == "fake":
        if not row.get("resume_bitwise"):
            reasons.append("multi-process-topology resume is not bitwise")
        if not row.get("rng_layout_free"):
            reasons.append("minibatch RNG root changed across layouts")
        if not row.get("manifest_stamped"):
            reasons.append("process layout missing from the manifest")
        if not row.get("single_block_rejected"):
            reasons.append("a lone per-process block restored silently")
        if not row.get("variants_ok"):
            reasons.append("a W2/kernel-approx variant went non-finite")
        if row.get("steps_lost") != row.get("expected_steps_lost"):
            reasons.append(
                f"steps_lost {row.get('steps_lost')} != expected "
                f"{row.get('expected_steps_lost')}")
        if (row.get("sentry_supported")
                and row.get("post_restart_recompiles", 0) != 0):
            reasons.append(
                f"{row['post_restart_recompiles']} post-restart "
                "steady-state recompile(s)")
    else:
        if not row.get("resume_t_complete"):
            reasons.append("a surviving worker did not regain the full "
                           "step grid")
        if row.get("federation_restarts") != 1:
            reasons.append(
                f"expected exactly one federation restart, got "
                f"{row.get('federation_restarts')}")
    if not row.get("killone_within_tol"):
        reasons.append(
            f"kill-one resume diverged (max dev {row.get('killone_max_dev')})")
    return not reasons, reasons


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("auto", "fake", "real"),
                    default="auto")
    ap.add_argument("--processes", type=int, default=4)
    ap.add_argument("--devcount", type=int, default=2,
                    help="devices per worker process")
    ap.add_argument("--n", type=int, default=288)
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--checkpoint-every", type=int, default=8)
    ap.add_argument("--kill-step", type=int, default=None)
    ap.add_argument("--stepsize", type=float, default=0.05)
    ap.add_argument("--tol", type=float, default=DEFAULT_TOL)
    ap.add_argument("--root", default=None)
    args = ap.parse_args()

    row = run_drill(
        mode=args.mode, processes=args.processes, devcount=args.devcount,
        n=args.n, num_steps=args.steps,
        checkpoint_every=args.checkpoint_every, kill_step=args.kill_step,
        step_size=args.stepsize, tol=args.tol, root=args.root,
    )
    ok, reasons = row_ok(row)
    row["ok"] = ok
    row["fail_reasons"] = reasons if not ok else []
    print(json.dumps(row), flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
