"""Reproduce the 100k/1M-particle single-chip rows of docs/notes.md.

Runs the full fused sampler step (Pallas φ + ``vmap(grad)`` banana scores)
at large n on one chip, where the kernel's VMEM tile streaming is the whole
story: the n² Gram matrix (4 TB f32 at n=1M) never exists.  Timing per the
repo protocol: chained scanned dispatches, scalar-fetch fenced, best of
``--samples``.

Usage: ``python tools/large_n.py [--n 100000] [--steps 10] [--samples 3]``
(n=1M takes ~6 s/step — budget a minute per sample).
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np
import jax
import jax.numpy as jnp

import dist_svgd_tpu as dt
from dist_svgd_tpu.models.logreg import make_logreg_logp
from dist_svgd_tpu.utils.datasets import load_benchmark


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--steps", type=int, default=10,
                    help="steps per timed dispatch")
    ap.add_argument("--samples", type=int, default=3)
    args = ap.parse_args()

    print("devices:", jax.devices(), flush=True)
    fold = load_benchmark("banana", 42)
    logp = make_logreg_logp(fold.x_train, fold.t_train.reshape(-1))
    d = 1 + fold.x_train.shape[1]
    n = args.n
    sampler = dt.Sampler(d, logp)

    def run_once(parts):
        out, _ = sampler.run(
            n, args.steps, 3e-3, record=False, initial_particles=parts
        )
        return out

    parts = jax.random.normal(jax.random.PRNGKey(0), (n, d), dtype=jnp.float32)
    out = run_once(parts)
    np.asarray(out)[0, 0]  # compile + fence, untimed
    best = float("inf")
    for _ in range(args.samples):
        t0 = time.perf_counter()
        out = run_once(out)  # state-chained: no dispatch can be elided
        np.asarray(out)[0, 0]
        best = min(best, (time.perf_counter() - t0) / args.steps)
    print(
        f"n={n}: {best*1e3:.1f} ms/step  "
        f"({n*n/best/1e9:.0f} G pairs/s, {n/best/1e6:.2f}M updates/s)",
        flush=True,
    )


if __name__ == "__main__":
    main()
