"""Reproduce the 100k/1M-particle single-chip rows of docs/notes.md.

Runs the full fused sampler step (Pallas φ + ``vmap(grad)`` banana scores)
at large n on one chip, where the kernel's VMEM tile streaming is the whole
story: the n² Gram matrix (4 TB f32 at n=1M) never exists.  Timing per the
repo protocol: chained scanned dispatches, scalar-fetch fenced, best of
``--samples``.

Usage: ``python tools/large_n.py [--n 100000] [--steps 10] [--samples 3]``
(n=1M takes ~6 s/step — budget a minute per sample).

``--w2`` instead measures the 8-shard scanned **Sinkhorn-W2** step at the
same n via the O(n·d)-memory streaming solve with warm-started duals
(``ops/pallas_ot.py``; each shard's (n/8, n) kernel matrix — 500 GB at
n=1M — never exists).  Budget minutes per sample at n=1M: a W2 step is
~5 streamed passes over n²/8 pairs even fully warm.
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np
import jax
import jax.numpy as jnp

import dist_svgd_tpu as dt
from dist_svgd_tpu.models.logreg import make_logreg_logp
from dist_svgd_tpu.utils.datasets import load_benchmark


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--steps", type=int, default=10,
                    help="steps per timed dispatch")
    ap.add_argument("--samples", type=int, default=3)
    ap.add_argument("--w2", action="store_true",
                    help="measure the 8-shard scanned Sinkhorn-W2 step "
                         "(streaming solve, warm duals) instead of the "
                         "plain step")
    ap.add_argument("--exchange", default="all_particles",
                    type=str, choices=["all_particles", "partitions"],
                    help="W2 exchange mode.  all_particles pairs each block "
                         "against the full previous set ((n/8, n) solves; "
                         "its gathered-set and snapshot buffers cap n at "
                         "~100k–200k on one chip — TPU lane padding makes "
                         "every (n, d) array n×128 floats).  partitions "
                         "pairs blocks against block snapshots ((n/8, n/8) "
                         "solves, block-sized state — the reference's own "
                         "per-rank W2 pairing), viable at n = 1M+")
    ap.add_argument("--exchange-impl", default="gather",
                    choices=["gather", "ring"],
                    help="all_* exchange implementation for --w2.  'ring' "
                         "composes with the block W2 pairing only: blockwise "
                         "ppermute φ + block-sized W2 state — no gathered "
                         "(n, d) set at all, the fully O(n/S)-memory step")
    ap.add_argument("--w2-pairing", default="auto",
                    choices=["auto", "global", "block"],
                    help="exchanged-mode W2 pairing (DistSampler.w2_pairing)."
                         "  'auto' routes to the block pairing above the "
                         "measured 400k global-pairing ceiling with a "
                         "warning; 'global' forces the reference pairing "
                         "onto the cliff (the A/B for the scaling table); "
                         "'block' forces the scalable pairing at any n")
    ap.add_argument("--stepsize", type=float, default=3e-3)
    ap.add_argument("--sinkhorn-iters", type=int, default=200,
                    help="per-step solve iteration cap.  At n = 1M a COLD "
                         "solve (~50 streamed passes) exceeds the tunnel's "
                         "single-dispatch watchdog; capping to ~8 splits it "
                         "across steps — the carried dual makes the solve "
                         "resumable, converging incrementally while "
                         "particles barely move (inexact JKO proximal "
                         "steps; docs/notes.md round-4)")
    args = ap.parse_args()

    print("devices:", jax.devices(), flush=True)
    fold = load_benchmark("banana", 42)
    d = 1 + fold.x_train.shape[1]
    n = args.n

    if args.w2:
        from dist_svgd_tpu.models.logreg import logreg_logp
        from dist_svgd_tpu.utils.rng import init_particles_per_shard

        S = 8
        if (args.exchange_impl == "ring" and args.exchange != "partitions"
                and args.w2_pairing == "auto" and args.n <= 400_000):
            # 'auto' resolves to the global pairing below the route
            # threshold, which the ring implementation rejects (its
            # snapshot is the gathered set) — the only pairing ring can
            # measure is 'block', so select it rather than erroring after
            # construction
            print("exchange-impl=ring: resolving --w2-pairing auto -> "
                  "block (the only ring-compatible pairing)", flush=True)
            args.w2_pairing = "block"
        ds = dt.DistSampler(
            S, logreg_logp, None, init_particles_per_shard(0, n, d, S),
            data=(jnp.asarray(fold.x_train),
                  jnp.asarray(fold.t_train.reshape(-1))),
            exchange_particles=(args.exchange != "partitions"),
            exchange_scores=False,
            include_wasserstein=True, wasserstein_solver="sinkhorn",
            sinkhorn_iters=args.sinkhorn_iters,
            w2_pairing=args.w2_pairing,
            exchange_impl=args.exchange_impl,
        )
        # warm up with SINGLE-step dispatches: the very first steps solve
        # cold (w_on=0 placeholder, then a full cold solve) and at n = 1M a
        # multi-step cold dispatch runs long enough to trip the tunnel's
        # execution watchdog (observed as "TPU worker crashed") — warm
        # steps are several times faster and chain safely
        for _ in range(max(args.steps, 2)):
            np.asarray(ds.run_steps(1, args.stepsize, h=10.0))[0, 0]
        # compile the args.steps-length scan untimed (run_steps compiles one
        # program per num_steps; the solve is warm by now so the multi-step
        # dispatch stays under the watchdog)
        np.asarray(ds.run_steps(args.steps, args.stepsize, h=10.0))[0, 0]
        best = float("inf")
        for _ in range(args.samples):
            t0 = time.perf_counter()
            np.asarray(ds.run_steps(args.steps, args.stepsize, h=10.0))[0, 0]
            best = min(best, (time.perf_counter() - t0) / args.steps)
        print(
            f"n={n} W2 streaming warm ({args.exchange}/{args.exchange_impl}, "
            f"pairing {ds._w2_pairing}, S={S}, stepsize "
            f"{args.stepsize}): {best*1e3:.0f} ms/step "
            f"({n/best/1e3:.0f}k updates/s)",
            flush=True,
        )
        return

    logp = make_logreg_logp(fold.x_train, fold.t_train.reshape(-1))
    sampler = dt.Sampler(d, logp)

    def run_once(parts):
        out, _ = sampler.run(
            n, args.steps, args.stepsize, record=False, initial_particles=parts
        )
        return out

    parts = jax.random.normal(jax.random.PRNGKey(0), (n, d), dtype=jnp.float32)
    out = run_once(parts)
    np.asarray(out)[0, 0]  # compile + fence, untimed
    best = float("inf")
    for _ in range(args.samples):
        t0 = time.perf_counter()
        out = run_once(out)  # state-chained: no dispatch can be elided
        np.asarray(out)[0, 0]
        best = min(best, (time.perf_counter() - t0) / args.steps)
    print(
        f"n={n}: {best*1e3:.1f} ms/step  "
        f"({n*n/best/1e9:.0f} G pairs/s, {n/best/1e6:.2f}M updates/s)",
        flush=True,
    )


if __name__ == "__main__":
    main()
