"""Reproduce the 100k–4M single-chip rows of docs/notes.md.

Runs the full fused sampler step (Pallas φ + ``vmap(grad)`` banana scores)
at large n on one chip, where the kernel's VMEM tile streaming is the whole
story: the n² Gram matrix (4 TB f32 at n=1M) never exists.  Timing per the
repo protocol: chained scanned dispatches, scalar-fetch fenced, best of
``--samples``.

Usage: ``python tools/large_n.py [--n 100000] [--steps 10] [--samples 3]``
(n=1M takes ~6 s/step — budget a minute per sample).

``--w2`` instead measures the sharded scanned **Sinkhorn-W2** step at the
same n via the O(n·d)-memory streaming solve with warm-started duals
(``ops/pallas_ot.py``; each shard's (n/8, n) kernel matrix — 500 GB at
n=1M — never exists).  Budget minutes per sample at n=1M: a W2 step is
~5 streamed passes over n²/8 pairs even fully warm.

**Chunked stepping** (the 2M/4M rows): past ~2M particles one step is a
single ≳60 s dispatch and the tunnel's execution watchdog kills it — pass
``--dispatch-budget <seconds>`` (auto-chunking via the measured pairs/sec
heuristic) or the explicit ``--hops-per-dispatch`` /
``--max-passes-per-dispatch`` knobs to run the same trajectory as a chain
of bounded dispatches (``DistSampler.run_steps(dispatch_budget=...)``;
requires ``--exchange-impl ring`` for the φ split).  ``--ab`` measures the
chunked execution **and** the monolithic one at the same config (the
chunking-overhead A/B — only meaningful where the monolithic dispatch
still clears the watchdog).  Every row is also emitted as a JSON record
(``--json-out`` appends to a file) carrying ``dispatches_per_step``,
``max_dispatch_wall_s``, and the **resolved** ``w2_pairing``.
"""

import argparse
import json
import math
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np
import jax
import jax.numpy as jnp

import dist_svgd_tpu as dt
from dist_svgd_tpu.distsampler import W2_GLOBAL_PAIRING_MAX_N
from dist_svgd_tpu.models.logreg import make_logreg_logp
from dist_svgd_tpu.utils.datasets import load_benchmark


def run_approx_row(n: int, method: str = "rff", num_features: int = 4096,
                   num_landmarks: int = 4096, steps: int = 5,
                   samples: int = 2, stepsize: float = 3e-3,
                   pin_n: int = 2048, exact_probe_n: int = 0,
                   seed: int = 0) -> dict:
    """The ``large_n_approx`` bench row: the sub-quadratic φ sampler step at
    a particle count the exact O(n²) kernel cannot touch on the same
    budget, with the approximation pinned against the exact kernel at
    small n.  Three measurements in one record:

    - **throughput** — full fused sampler steps (banana logreg scores +
      approximate φ) at ``n``, the repo's chained-dispatch protocol, under
      the retrace sentry (any steady-state compile in the timed window ⇒
      ``recompiles`` > 0, an unconditional ``perf_regress`` FAIL);
    - **error pin** — relative φ error of THIS configuration (same method,
      dial, and — for RFF — the same ``seed``-derived bank) vs the exact
      kernel on the canonical small-n probe
      (``ops/approx.py:error_pin_probe``), judged against the declared
      budget (``default_error_budget``): outside budget ⇒ unconditional
      FAIL;
    - **exact extrapolation** — the exact kernel measured at
      ``exact_probe_n`` (default ``min(n, 65536)``), giving a pairs/sec
      rate that extrapolates quadratically to ``n`` —
      ``exact_est_wall_per_step_s`` / ``est_speedup_vs_exact`` quantify
      the wall the approximation removes.
    """
    from dist_svgd_tpu.ops.approx import (
        KernelApprox,
        default_error_budget,
        error_pin_probe,
        make_approx_phi_fn,
        phi_rel_error,
    )
    from dist_svgd_tpu.ops.svgd import phi as phi_exact
    from dist_svgd_tpu.utils.rng import approx_bank_key, init_particles
    from tools.jaxlint.sentry import retrace_sentry

    if method == "rff":
        spec = KernelApprox("rff", num_features=num_features)
        dial = num_features
    else:
        spec = KernelApprox("nystrom", num_landmarks=num_landmarks)
        dial = num_landmarks
    fold = load_benchmark("banana", 42)
    d = 1 + fold.x_train.shape[1]
    logp = make_logreg_logp(fold.x_train, fold.t_train.reshape(-1))
    sampler = dt.Sampler(d, logp, kernel_approx=spec, phi_impl="xla")

    def chain(s, parts, num_steps):
        out, _ = s.run(parts.shape[0], num_steps, stepsize, seed=seed,
                       record=False, initial_particles=parts)
        return out

    parts = init_particles(seed, n, d, dtype=jnp.float32)
    parts = chain(sampler, parts, steps)
    np.asarray(parts)[0, 0]  # compile + fence, untimed
    best = float("inf")
    with retrace_sentry("large_n_approx timed window") as sentry:
        for _ in range(samples):
            t0 = time.perf_counter()
            parts = chain(sampler, parts, steps)
            np.asarray(parts)[0, 0]
            best = min(best, (time.perf_counter() - t0) / steps)

    # error pin at small n: same method/dial/bank as the measured config
    pin_spec = spec
    if method == "rff":
        pin_spec = spec.with_key(approx_bank_key(seed))
    px, ps, pk = error_pin_probe(pin_n, d, seed)
    err = phi_rel_error(phi_exact(px, px, ps, pk),
                        make_approx_phi_fn(pk, pin_spec)(px, px, ps))
    budget = default_error_budget(pin_spec, d)

    # exact-kernel probe → quadratic extrapolation to n
    probe_n = exact_probe_n or min(n, 65_536)
    exact = dt.Sampler(d, logp)
    eparts = init_particles(seed, probe_n, d, dtype=jnp.float32)
    eparts = chain(exact, eparts, steps)
    np.asarray(eparts)[0, 0]
    ebest = float("inf")
    for _ in range(samples):
        t0 = time.perf_counter()
        eparts = chain(exact, eparts, steps)
        np.asarray(eparts)[0, 0]
        ebest = min(ebest, (time.perf_counter() - t0) / steps)
    pairs_per_sec = probe_n * probe_n / ebest
    exact_est = n * n / pairs_per_sec

    return {
        "bench": "large_n_approx", "n": n, "method": method, "dial": dial,
        "d": d, "stepsize": stepsize, "steps_per_dispatch": steps,
        "wall_per_step_s": round(best, 6),
        "updates_per_sec": round(n / best, 1),
        "approx_rel_err": round(err, 6),
        "error_budget": round(budget, 6),
        "within_budget": bool(err <= budget),
        "pin_n": pin_n,
        "recompiles": sentry.compiles if sentry.supported else None,
        "sentry_supported": sentry.supported,
        "exact_probe_n": probe_n,
        "exact_probe_wall_per_step_s": round(ebest, 6),
        "exact_pairs_per_sec": round(pairs_per_sec, 1),
        "exact_est_wall_per_step_s": round(exact_est, 3),
        "est_speedup_vs_exact": round(exact_est / best, 1),
        "kernel_approx_active": sampler.kernel_approx_active,
    }


def approx_row_ok(row: dict) -> tuple:
    """Unconditional correctness gates of the ``large_n_approx`` row (the
    ``perf_regress`` discipline: these FAIL regardless of throughput).
    Returns ``(ok, reasons)``."""
    why = []
    if not row.get("within_budget"):
        why.append(
            f"approximation error {row.get('approx_rel_err')} exceeds the "
            f"declared budget {row.get('error_budget')} at the small-n pin"
        )
    if row.get("sentry_supported") and row.get("recompiles"):
        why.append(
            f"{row['recompiles']} steady-state recompile(s) in the timed "
            "window — a retrace bug contaminating the measurement"
        )
    wall = row.get("wall_per_step_s")
    if not (isinstance(wall, (int, float)) and math.isfinite(wall)
            and wall > 0):
        why.append(f"non-finite wall_per_step_s {wall!r}")
    if not row.get("kernel_approx_active"):
        why.append("the approximate backend was not active — the row "
                   "measured the exact kernel")
    return (not why), why


def resolve_ring_pairing(n: int, exchange: str, exchange_impl: str,
                         w2_pairing: str) -> str:
    """Pre-resolve ``--w2-pairing auto`` for the ring implementation.

    'auto' resolves to the global pairing at or below
    :data:`~dist_svgd_tpu.distsampler.W2_GLOBAL_PAIRING_MAX_N` (the same
    constant the library routes on — compared directly so the tool cannot
    silently desync from it, ADVICE round 5), which the ring implementation
    rejects (its snapshot is the gathered set) — the only pairing ring can
    measure is 'block', so select it here rather than erroring after
    construction."""
    if (exchange_impl == "ring" and exchange != "partitions"
            and w2_pairing == "auto" and n <= W2_GLOBAL_PAIRING_MAX_N):
        return "block"
    return w2_pairing


def emit(record: dict, json_out) -> None:
    line = json.dumps(record)
    print(line, flush=True)
    if json_out:
        with open(json_out, "a") as f:
            f.write(line + "\n")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--steps", type=int, default=10,
                    help="steps per timed dispatch")
    ap.add_argument("--samples", type=int, default=3)
    ap.add_argument("--shards", type=int, default=8,
                    help="mesh size S for --w2 (vmap-emulated on one chip)."
                         "  Chunking granularity is S: at 2M+ raising S "
                         "shrinks the per-hop dispatch (n²/S pairs)")
    ap.add_argument("--w2", action="store_true",
                    help="measure the sharded scanned Sinkhorn-W2 step "
                         "(streaming solve, warm duals) instead of the "
                         "plain step")
    ap.add_argument("--exchange", default="all_particles",
                    type=str, choices=["all_particles", "partitions"],
                    help="W2 exchange mode.  all_particles pairs each block "
                         "against the full previous set ((n/8, n) solves; "
                         "its gathered-set and snapshot buffers cap n at "
                         "~100k–200k on one chip — TPU lane padding makes "
                         "every (n, d) array n×128 floats).  partitions "
                         "pairs blocks against block snapshots ((n/8, n/8) "
                         "solves, block-sized state — the reference's own "
                         "per-rank W2 pairing), viable at n = 1M+")
    ap.add_argument("--exchange-impl", default="gather",
                    choices=["gather", "ring"],
                    help="all_* exchange implementation for --w2.  'ring' "
                         "composes with the block W2 pairing only: blockwise "
                         "ppermute φ + block-sized W2 state — no gathered "
                         "(n, d) set at all, the fully O(n/S)-memory step, "
                         "and the only implementation with an intra-step "
                         "seam for --dispatch-budget / --hops-per-dispatch")
    ap.add_argument("--w2-pairing", default="auto",
                    choices=["auto", "global", "block"],
                    help="exchanged-mode W2 pairing (DistSampler.w2_pairing)."
                         "  'auto' routes to the block pairing above the "
                         "measured global-pairing ceiling "
                         f"({W2_GLOBAL_PAIRING_MAX_N}) with a warning; "
                         "'global' forces the reference pairing onto the "
                         "cliff (the A/B for the scaling table); 'block' "
                         "forces the scalable pairing at any n")
    ap.add_argument("--stepsize", type=float, default=3e-3)
    ap.add_argument("--sinkhorn-iters", type=int, default=200,
                    help="per-step solve iteration cap.  At n = 1M a COLD "
                         "solve (~50 streamed passes) exceeds the tunnel's "
                         "single-dispatch watchdog; capping to ~8 splits it "
                         "across steps — the carried dual makes the solve "
                         "resumable, converging incrementally while "
                         "particles barely move (inexact JKO proximal "
                         "steps; docs/notes.md round-4).  With "
                         "--max-passes-per-dispatch the cap no longer needs "
                         "to double as the dispatch bound")
    ap.add_argument("--dispatch-budget", type=float, default=None,
                    help="per-dispatch wall budget (seconds): auto-chunk "
                         "the step so no single dispatch exceeds it "
                         "(run_steps dispatch_budget; keep it well under "
                         "the ~60 s watchdog — 10–20 s is comfortable)")
    ap.add_argument("--pairs-per-sec", type=float, default=None,
                    help="pair-throughput estimate feeding the budget "
                         "heuristic (default: the measured v5e rate, "
                         "distsampler.DISPATCH_PAIRS_PER_SEC)")
    ap.add_argument("--hops-per-dispatch", type=int, default=None,
                    help="explicit ring-hop chunk size (1..S); bypasses "
                         "the budget heuristic")
    ap.add_argument("--max-passes-per-dispatch", type=int, default=None,
                    help="explicit Sinkhorn pass chunk size; bypasses the "
                         "budget heuristic")
    ap.add_argument("--ab", action="store_true",
                    help="chunked-vs-monolithic A/B: measure both "
                         "executions at this config and emit both records")
    ap.add_argument("--kernel-approx", default=None,
                    choices=["rff", "nystrom"],
                    help="measure the sub-quadratic φ instead of the exact "
                         "kernel: the large_n_approx row (throughput at n, "
                         "small-n error pin vs the exact kernel, quadratic "
                         "exact-wall extrapolation)")
    ap.add_argument("--num-features", type=int, default=4096,
                    help="RFF accuracy dial R (kernel-approx rff)")
    ap.add_argument("--num-landmarks", type=int, default=4096,
                    help="Nyström accuracy dial L (kernel-approx nystrom)")
    ap.add_argument("--approx-pin-n", type=int, default=2048,
                    help="small-n size of the exact-vs-approx error pin")
    ap.add_argument("--exact-probe-n", type=int, default=0,
                    help="exact-kernel probe size for the quadratic wall "
                         "extrapolation (0 = min(n, 65536))")
    ap.add_argument("--json-out", type=str, default=None,
                    help="append one JSON record per measured row here")
    args = ap.parse_args()

    print("devices:", jax.devices(), flush=True)
    if args.kernel_approx is not None:
        record = run_approx_row(
            args.n, method=args.kernel_approx,
            num_features=args.num_features,
            num_landmarks=args.num_landmarks, steps=args.steps,
            samples=args.samples, stepsize=args.stepsize,
            pin_n=args.approx_pin_n, exact_probe_n=args.exact_probe_n,
        )
        emit(record, args.json_out)
        ok, why = approx_row_ok(record)
        print(
            f"n={args.n} {args.kernel_approx} (dial {record['dial']}): "
            f"{record['wall_per_step_s']*1e3:.1f} ms/step "
            f"({record['updates_per_sec']/1e6:.2f}M updates/s), pin err "
            f"{record['approx_rel_err']:.4f} <= budget "
            f"{record['error_budget']:.4f}: {record['within_budget']}; "
            f"exact est {record['exact_est_wall_per_step_s']:.1f} s/step "
            f"(~{record['est_speedup_vs_exact']:.0f}x)"
            + ("" if ok else f"  GATE: {'; '.join(why)}"),
            flush=True,
        )
        return
    fold = load_benchmark("banana", 42)
    d = 1 + fold.x_train.shape[1]
    n = args.n
    chunk_kwargs = {}
    if args.dispatch_budget is not None:
        chunk_kwargs = dict(dispatch_budget=args.dispatch_budget,
                            pairs_per_sec=args.pairs_per_sec)
    elif (args.hops_per_dispatch is not None
          or args.max_passes_per_dispatch is not None):
        chunk_kwargs = dict(
            hops_per_dispatch=args.hops_per_dispatch,
            max_passes_per_dispatch=args.max_passes_per_dispatch)
    chunked = bool(chunk_kwargs)

    if args.w2:
        from dist_svgd_tpu.models.logreg import logreg_logp
        from dist_svgd_tpu.utils.rng import init_particles_per_shard

        S = args.shards
        resolved = resolve_ring_pairing(
            args.n, args.exchange, args.exchange_impl, args.w2_pairing)
        if resolved != args.w2_pairing:
            print("exchange-impl=ring: resolving --w2-pairing auto -> "
                  "block (the only ring-compatible pairing)", flush=True)
            args.w2_pairing = resolved
        ds = dt.DistSampler(
            S, logreg_logp, None, init_particles_per_shard(0, n, d, S),
            data=(jnp.asarray(fold.x_train),
                  jnp.asarray(fold.t_train.reshape(-1))),
            exchange_particles=(args.exchange != "partitions"),
            exchange_scores=False,
            include_wasserstein=True, wasserstein_solver="sinkhorn",
            sinkhorn_iters=args.sinkhorn_iters,
            w2_pairing=args.w2_pairing,
            exchange_impl=args.exchange_impl,
        )

        def run_block(num_steps, **kw):
            np.asarray(ds.run_steps(num_steps, args.stepsize, h=10.0,
                                    **kw))[0, 0]

        # warm up with SINGLE-step dispatches: the very first steps solve
        # cold (w_on=0 placeholder, then a full cold solve) and at n = 1M a
        # multi-step cold dispatch runs long enough to trip the tunnel's
        # execution watchdog (observed as "TPU worker crashed") — warm
        # steps are several times faster and chain safely.  Chunked warmup
        # uses the chunked executor itself, so even the cold solve stays
        # under the budget
        for _ in range(max(args.steps, 2)):
            run_block(1, **chunk_kwargs)

        def measure(kw, fenced_stats=False):
            """Compile untimed, then best-of-samples.  The throughput
            timing never fences per dispatch (time_dispatches would block
            the chain and bill the relay round-trips to the chunked leg —
            the A/B must compare pipelined executions); per-dispatch walls
            come from ONE extra fenced run afterwards."""
            run_block(args.steps, **kw)
            best = float("inf")
            for _ in range(args.samples):
                t0 = time.perf_counter()
                run_block(args.steps, **kw)
                best = min(best, (time.perf_counter() - t0) / args.steps)
            stats = ds.last_run_stats
            if fenced_stats:
                run_block(args.steps, **dict(kw, time_dispatches=True))
                stats = ds.last_run_stats
            return best, stats

        variants = []
        if chunked:
            variants.append(("chunked", chunk_kwargs))
            if args.ab:
                variants.append(("monolithic", {}))
        else:
            variants.append(("monolithic", {}))
            if args.ab:
                variants.append(("chunked", dict(hops_per_dispatch=1)))
        for label, kw in variants:
            best, stats = measure(kw, fenced_stats=(label == "chunked"))
            record = {
                "bench": "large_n_w2", "n": n, "num_shards": S,
                "execution": label, "exchange": args.exchange,
                "exchange_impl": args.exchange_impl,
                "w2_pairing": ds.w2_pairing,
                "sinkhorn_iters": args.sinkhorn_iters,
                "stepsize": args.stepsize,
                "wall_per_step_s": round(best, 4),
                "updates_per_sec": round(n / best, 1),
            }
            if stats is not None and label == "chunked":
                record.update({
                    "dispatches_per_step": stats["dispatches_per_step"],
                    "num_dispatches": stats["num_dispatches"],
                    "max_dispatch_wall_s":
                        None if stats["max_dispatch_wall_s"] is None
                        else round(stats["max_dispatch_wall_s"], 4),
                    "hops_per_dispatch": stats.get("hops_per_dispatch"),
                    "max_passes_per_dispatch":
                        stats.get("max_passes_per_dispatch"),
                    "dispatch_budget_s": stats.get("dispatch_budget_s"),
                })
            emit(record, args.json_out)
            print(
                f"n={n} W2 streaming warm ({args.exchange}/"
                f"{args.exchange_impl}, pairing {ds.w2_pairing}, S={S}, "
                f"stepsize {args.stepsize}, {label}): {best*1e3:.0f} "
                f"ms/step ({n/best/1e3:.0f}k updates/s)",
                flush=True,
            )
        return

    logp = make_logreg_logp(fold.x_train, fold.t_train.reshape(-1))
    sampler = dt.Sampler(d, logp)

    def run_once(parts):
        out, _ = sampler.run(
            n, args.steps, args.stepsize, record=False,
            initial_particles=parts,
            dispatch_budget=args.dispatch_budget,
            pairs_per_sec=args.pairs_per_sec,
        )
        return out

    from dist_svgd_tpu.utils.rng import init_particles

    parts = init_particles(0, n, d, dtype=jnp.float32)
    out = run_once(parts)
    np.asarray(out)[0, 0]  # compile + fence, untimed
    best = float("inf")
    for _ in range(args.samples):
        t0 = time.perf_counter()
        out = run_once(out)  # state-chained: no dispatch can be elided
        np.asarray(out)[0, 0]
        best = min(best, (time.perf_counter() - t0) / args.steps)
    stats = sampler.last_run_stats or {}
    emit({
        "bench": "large_n_phi", "n": n, "stepsize": args.stepsize,
        "execution": stats.get("execution", "monolithic"),
        "num_dispatches": stats.get("num_dispatches"),
        "dispatches_per_step": stats.get("dispatches_per_step"),
        "wall_per_step_s": round(best, 6),
        "pairs_per_sec": round(n * n / best, 1),
        "updates_per_sec": round(n / best, 1),
    }, args.json_out)
    print(
        f"n={n}: {best*1e3:.1f} ms/step  "
        f"({n*n/best/1e9:.0f} G pairs/s, {n/best/1e6:.2f}M updates/s)",
        flush=True,
    )


if __name__ == "__main__":
    main()
