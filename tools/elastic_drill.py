"""Elastic-capacity drill: device-loss → reshard → resume → serve, emitting
ONE BENCH-style ``elastic_resume`` JSON row.

The resilience drill (``tools/fault_drill.py``) measures recovery at the
SAME topology; this one measures the missing half of production robustness
— losing a device (the most common real TPU failure) and coming back on a
*smaller* mesh instead of dying with the restart budget.  Phases (GMM
posterior, every fault injected via ``resilience/faults.py`` — CPU and TPU
both fine):

1. **baseline** — a supervised, checkpointed run at ``shards_from`` to
   completion (after an untimed warm-up), with posterior diagnostics at the
   checkpoint cadence: the reference trajectory and its final KSD/ESS;
2. **shrink** — the same run with an injected ``MeshShrinkAt`` mid-way
   between checkpoints: the supervisor's ``ReshardPolicy`` reshards the
   latest checkpoint to ``shards_to`` (``utils/checkpoint.py:
   reshard_state``) and continues inside the restart budget.  The row
   records **steps lost** (replayed since the last checkpoint), **reshard
   wall** (restore + reshard + rebuild + load) and **recovery wall**
   (reshard + backoff + replay to the detection step), plus the
   post-reshard KSD/ESS deltas and max particle deviation vs baseline;
3. **steady state** — a continuation run on the resharded sampler under the
   retrace sentry: after the ONE reshard compile, steady-state segments at
   the new topology must compile NOTHING (``post_reshard_recompiles``);
4. **grow** — the recovery direction: a ``shards_grow_from``-shard run hit
   by ``MeshGrowAt`` back to ``shards_from``, pinned against its own
   uninterrupted baseline;
5. **fallback** — a shrink to a shard count that does NOT divide n takes
   ``Plan.shard_ensemble``'s replicate-and-warn fallback (the run lands at
   1 shard, correct but undistributed) instead of crashing;
6. **serve** — ``PredictiveEngine.from_checkpoint`` cold-starts from the
   post-reshard manager root (the topology manifest rides the same dict)
   and must serve finite predictions from the full ensemble.

Usage::

    python tools/elastic_drill.py                 # n=2048, 8 -> 4, 48 steps
    python tools/elastic_drill.py --n 1024 --shards-from 4 --shards-to 2
"""

import argparse
import json
import os
import sys
import time
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.fault_drill import build_sampler, gmm_score_fn  # noqa: E402


def _delta_frac(a, b):
    if a is None or b is None:
        return None
    return round(abs(b - a) / max(abs(a), 1e-12), 6)


def run_drill(n=2048, shards_from=8, shards_to=4, num_steps=48,
              step_size=0.05, checkpoint_every=16, segment_steps=4,
              reshard_step=None, shards_grow_from=2, fallback_to=None,
              reshard_tol=1e-4, root=None, seed=0):
    """Run the six drill phases; returns the ``elastic_resume`` row."""
    import jax
    import numpy as np

    from dist_svgd_tpu.resilience import (
        FaultPlan,
        MeshGrowAt,
        MeshShrinkAt,
        ReshardPolicy,
        RunSupervisor,
    )
    from dist_svgd_tpu.serving.engine import PredictiveEngine
    from dist_svgd_tpu.telemetry import MetricsRegistry
    from dist_svgd_tpu.telemetry.diagnostics import (
        DiagnosticsConfig,
        PosteriorDiagnostics,
    )
    from tools.jaxlint.sentry import retrace_sentry

    if root is None:
        import tempfile

        root = tempfile.mkdtemp(prefix="elastic_drill_")
    if reshard_step is None:
        # strictly between two checkpoints, like fault_drill's kill step:
        # the interesting case, where the reshard actually replays steps
        reshard_step = 2 * checkpoint_every + segment_steps
    if reshard_step >= num_steps:
        raise ValueError(
            f"reshard_step ({reshard_step}) must land before num_steps "
            f"({num_steps}) or the topology fault never fires"
        )
    if fallback_to is None:
        # smallest count > 1 that does not divide n (3 at the n=2048 default)
        fallback_to = next(m for m in range(2, n + 2) if n % m)

    registry = MetricsRegistry()
    diag = PosteriorDiagnostics(
        DiagnosticsConfig(every_steps=checkpoint_every,
                          score_fn=gmm_score_fn(),
                          row_chunk=512, max_points=512),
        registry=registry,
    )

    def factory(num_shards):
        return build_sampler(n, num_shards, seed)

    def supervise(sampler, steps, **kw):
        kw.setdefault("segment_steps", segment_steps)
        kw.setdefault("sleep", lambda s: None)  # injected faults only
        kw.setdefault("registry", registry)
        return RunSupervisor(sampler, steps, step_size, **kw)

    # -------- phase 1: baseline at shards_from ------------------------- #
    ds = build_sampler(n, shards_from, seed)
    state0 = ds.state_dict()
    supervise(ds, num_steps, manager=None, diagnostics=diag).run()  # warm-up
    ds.load_state_dict(state0)
    sup_b = supervise(ds, num_steps, checkpoint_dir=os.path.join(root, "base"),
                      checkpoint_every=checkpoint_every, diagnostics=diag)
    base = sup_b.run()
    final_baseline = np.asarray(sup_b.particles)
    step_wall_s = base["segment_wall_s"] / max(base["steps_run"], 1)
    diag_b = base["last_diagnostics"] or {}

    # -------- phase 2: shrink mid-run ---------------------------------- #
    ds2 = build_sampler(n, shards_from, seed)
    elastic_dir = os.path.join(root, "elastic")
    sup_e = supervise(ds2, num_steps, checkpoint_dir=elastic_dir,
                      checkpoint_every=checkpoint_every, diagnostics=diag,
                      reshard=ReshardPolicy(factory),
                      faults=FaultPlan(MeshShrinkAt(reshard_step, shards_to)))
    elastic = sup_e.run()
    assert elastic["reshards"] == 1, elastic
    event = elastic["reshard_events"][0]
    final_elastic = np.asarray(sup_e.particles)
    max_dev = float(np.abs(final_baseline - final_elastic).max())
    diag_e = elastic["last_diagnostics"] or {}
    # the replicated hyperparameters must survive the reshard bitwise:
    # step counter, (possibly backed-off) step size, minibatch RNG root,
    # resolved W2 pairing code — everything the row's name promises
    st_b, st_e = sup_b._harness.state_dict(), sup_e._harness.state_dict()
    hyper_bitwise = (
        elastic["t"] == base["t"]
        and sup_e.step_size == sup_b.step_size
        and np.array_equal(st_b["rng_batch_key"], st_e["rng_batch_key"])
        and np.array_equal(st_b["w2_pairing"], st_e["w2_pairing"])
    )

    # -------- phase 3: post-reshard steady state (retrace sentry) ------ #
    # the resharded sampler's programs compiled during phase 2's replay —
    # further segments at the new topology must compile nothing
    sup_c = supervise(sup_e.sampler, num_steps + 2 * segment_steps,
                      manager=None)
    with retrace_sentry("post-reshard steady state") as sentry:
        cont = sup_c.run()
    assert cont["status"] == "completed", cont

    # -------- phase 4: grow back --------------------------------------- #
    grow_steps = max(2 * checkpoint_every, 4 * segment_steps)
    grow_at = max(checkpoint_every // 2 + 1, segment_steps)
    gs = build_sampler(n, shards_grow_from, seed)
    sup_g0 = supervise(gs, grow_steps,
                       checkpoint_dir=os.path.join(root, "grow_base"),
                       checkpoint_every=checkpoint_every)
    sup_g0.run()
    gs2 = build_sampler(n, shards_grow_from, seed)
    sup_g = supervise(gs2, grow_steps,
                      checkpoint_dir=os.path.join(root, "grow"),
                      checkpoint_every=checkpoint_every,
                      reshard=ReshardPolicy(factory),
                      faults=FaultPlan(MeshGrowAt(grow_at, shards_from)))
    grow = sup_g.run()
    grow_dev = float(np.abs(np.asarray(sup_g0.particles)
                            - np.asarray(sup_g.particles)).max())
    grow_ok = (grow["num_shards"] == shards_from and grow["reshards"] == 1
               and grow_dev <= reshard_tol)

    # -------- phase 5: non-dividing fallback --------------------------- #
    fs = build_sampler(n, shards_from, seed)
    sup_f = supervise(fs, grow_steps,
                      checkpoint_dir=os.path.join(root, "fallback"),
                      checkpoint_every=checkpoint_every,
                      reshard=ReshardPolicy(factory),
                      faults=FaultPlan(MeshShrinkAt(grow_at, fallback_to)))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        fb = sup_f.run()
    fallback_warned = any("replicating instead of sharding" in str(w.message)
                          for w in caught)
    fallback_ok = (fb["status"] == "completed" and fb["num_shards"] == 1
                   and fallback_warned)

    # -------- phase 6: serve from the post-reshard checkpoint ---------- #
    serve_wall0 = time.perf_counter()
    engine = PredictiveEngine.from_checkpoint(elastic_dir, model="gmm")
    queries = final_elastic[:8]
    out = engine.predict(queries)
    serve_wall_s = time.perf_counter() - serve_wall0
    serve_ok = (engine.n_particles == n
                and engine.checkpoint_step == num_steps
                and bool(np.isfinite(out["log_density"]).all()))

    recovery_wall = event.get("recovery_wall_s")
    return {
        "metric": "elastic_resume",
        "platform": jax.devices()[0].platform,
        "n": n,
        "shards_from": shards_from,
        "shards_to": event["to_shards"],
        "num_steps": num_steps,
        "checkpoint_every": checkpoint_every,
        "segment_steps": segment_steps,
        "reshard_step": event["t_detected"],
        "resumed_from": event["resumed_from"],
        "steps_lost": event["steps_lost"],
        "step_wall_ms": round(step_wall_s * 1e3, 3),
        "reshard_wall_s": event["reshard_wall_s"],
        "recovery_wall_s": recovery_wall,
        "recovery_vs_step_wall": (
            round(recovery_wall / max(step_wall_s, 1e-9), 1)
            if recovery_wall is not None else None),
        "elastic_final_max_dev": max_dev,
        "resumed_within_tolerance": bool(max_dev <= reshard_tol),
        "hyperparams_bitwise": bool(hyper_bitwise),
        "ksd_baseline": diag_b.get("ksd"),
        "ksd_elastic": diag_e.get("ksd"),
        "ksd_delta_frac": _delta_frac(diag_b.get("ksd"), diag_e.get("ksd")),
        "ess_frac_baseline": diag_b.get("ess_frac"),
        "ess_frac_elastic": diag_e.get("ess_frac"),
        "ess_frac_delta": _delta_frac(diag_b.get("ess_frac"),
                                      diag_e.get("ess_frac")),
        "post_reshard_recompiles": sentry.compiles,
        "sentry_supported": sentry.supported,
        "grow_from": shards_grow_from,
        "grow_to": shards_from,
        "grow_max_dev": grow_dev,
        "grow_ok": bool(grow_ok),
        "fallback_requested": fallback_to,
        "fallback_to_shards": fb["num_shards"],
        "fallback_warned": bool(fallback_warned),
        "fallback_ok": bool(fallback_ok),
        "serve_wall_s": round(serve_wall_s, 4),
        "serve_ok": bool(serve_ok),
        "restarts": elastic["restarts"],
        "elastic_reshards_total": registry.counter(
            "svgd_elastic_reshards_total").value(direction="shrink")
        + registry.counter("svgd_elastic_reshards_total").value(
            direction="grow"),
        "elastic_steps_lost_total": registry.counter(
            "svgd_elastic_steps_lost_total").value(),
    }


def drill_ok(row) -> bool:
    """The drill's own acceptance: exact-enough resume, clean steady state,
    both directions, graceful fallback, serving from the resharded save."""
    return bool(
        row["resumed_within_tolerance"]
        and row["hyperparams_bitwise"]
        and (not row["sentry_supported"] or row["post_reshard_recompiles"] == 0)
        and row["grow_ok"]
        and row["fallback_ok"]
        and row["serve_ok"]
    )


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=2048)
    ap.add_argument("--shards-from", type=int, default=8)
    ap.add_argument("--shards-to", type=int, default=4)
    ap.add_argument("--steps", type=int, default=48)
    ap.add_argument("--stepsize", type=float, default=0.05)
    ap.add_argument("--checkpoint-every", type=int, default=16)
    ap.add_argument("--segment-steps", type=int, default=4)
    ap.add_argument("--reshard-step", type=int, default=None)
    ap.add_argument("--grow-from", type=int, default=2)
    ap.add_argument("--fallback-to", type=int, default=None,
                    help="non-dividing shard target for the fallback phase "
                         "(default: smallest count > 1 that doesn't divide n)")
    ap.add_argument("--tol", type=float, default=1e-4,
                    help="max particle deviation accepted vs the "
                         "never-resharded run (float accumulation-order "
                         "noise across shard counts; bitwise is not "
                         "expected, exactness is pinned by the tests)")
    ap.add_argument("--root", default=None,
                    help="checkpoint scratch root (default: a temp dir)")
    args = ap.parse_args()

    row = run_drill(
        n=args.n, shards_from=args.shards_from, shards_to=args.shards_to,
        num_steps=args.steps, step_size=args.stepsize,
        checkpoint_every=args.checkpoint_every,
        segment_steps=args.segment_steps, reshard_step=args.reshard_step,
        shards_grow_from=args.grow_from, fallback_to=args.fallback_to,
        reshard_tol=args.tol, root=args.root,
    )
    print(json.dumps(row), flush=True)
    sys.exit(0 if drill_ok(row) else 1)


if __name__ == "__main__":
    main()
