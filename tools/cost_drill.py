"""Cost-attribution drill: does the runtime cost ledger add up?

One multi-tenant serve window with the dispatch profiler
(``telemetry/profile.py``) and the usage meter (``telemetry/usage.py``)
both enabled, judged on three accounting identities plus an A/B
overhead bound — the ``cost_attribution`` row ``tools/perf_regress.py``
gates unconditionally:

- **attribution coverage** — fenced per-program dispatch wall attributed
  to ``serve.*`` plan labels must be >= 95% of the measured dispatch
  wall (the batcher's own ``svgd_serve_device_time_seconds`` window over
  the same batches).  The gap is un-attributed host work inside the
  dispatch window (padding, placement, fetch); a profiler that loses
  sight of where device time goes fails here.
- **tenant sum** — per-tenant ``svgd_usage_device_seconds_total`` must
  sum to the total measured device wall within 1%.  Both sides derive
  from the same per-batch measurement, so this is an accounting
  identity: a mismatch means a batch was metered twice, dropped, or
  mislabelled — not noise.
- **zero in-window recompiles** — warmed steady state must stay
  compile-free with both instruments on (kernel-cache miss counters,
  the usage ledger's compile counter, and the jaxlint retrace sentry all
  at zero over the window).
- **profiler overhead** — interleaved off/on closed-loop rounds over the
  same warmed serving stack, best-of each arm (serve_bench's
  ``measure_telemetry_overhead`` noise discipline); perf_regress FAILs
  the ``profiler_overhead`` row above its fixed 3% ceiling.

The window also exercises the telemetry-history loop end to end: a
clock-driven :class:`~dist_svgd_tpu.telemetry.history.HistoryRecorder`
snapshots the drill registry between window segments and
``tools/anomaly_report.py``'s detector runs over the recorded series
(report-only — a short drill window is too noisy to gate on; the
deterministic anomaly gates live in the fixture tests).

Tenants are sized differently on purpose (distinct ensemble sizes) so
per-tenant device-seconds are visibly unequal — a cost report in which
every tenant costs the same catches nothing.

Usage::

    python tools/cost_drill.py                 # human row + verdict
    python tools/cost_drill.py --json
    python tools/cost_drill.py --requests 600 --ab-rounds 3
    python tools/cost_drill.py --dump-metrics /tmp/dump.json   # then:
    python tools/trace_report.py --programs /tmp/dump.json

Exit code: 0 when every gate above holds, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import serve_bench  # noqa: E402
from tools.jaxlint import retrace_sentry  # noqa: E402

#: Fixed gates (see module docstring).
COVERAGE_MIN = 0.95
TENANT_SUM_TOL = 0.01
PROFILER_OVERHEAD_MAX = 0.03

#: Tenant ensembles: distinct sizes so the cost report has something to
#: distinguish.  (name, n_particles) — features are shared.
DEFAULT_TENANTS = (("alpha", 65536), ("bravo", 32768), ("charlie", 16384))


def build_serving(tenants=DEFAULT_TENANTS, n_features=32, max_batch=64,
                  registry=None, seed=0):
    """Per-tenant engines behind ONE micro-batcher (the registry path's
    shape, without its scanner machinery): single shared queue, tenant-
    routed dispatch, one padding bucket per engine (min=max) so warmup
    covers the whole steady state."""
    import numpy as np

    from dist_svgd_tpu.serving.batcher import MicroBatcher
    from dist_svgd_tpu.serving.engine import PredictiveEngine
    from dist_svgd_tpu.telemetry import metrics as _metrics

    registry = registry if registry is not None else _metrics.MetricsRegistry()
    rng = np.random.default_rng(seed)
    engines = {}
    for name, n_particles in tenants:
        parts = rng.normal(size=(n_particles, 1 + n_features)).astype(
            np.float32)
        engines[name] = PredictiveEngine(
            "logreg", parts, min_bucket=max_batch, max_bucket=max_batch,
            registry=registry, tenant=name)

    def dispatch(x, tenant=None):
        return engines[tenant].predict(x)

    batcher = MicroBatcher(dispatch, max_batch=max_batch, max_wait_ms=0.5,
                           registry=registry)
    return engines, batcher, registry


def _measured_device_seconds(registry):
    """The batcher's dispatch wall: sum of the
    ``svgd_serve_device_time_seconds`` histogram over every label set."""
    hist = registry.get("svgd_serve_device_time_seconds")
    if hist is None:
        return 0.0
    # microsecond scale: Histogram.summary rounds to 4 decimals
    return float(sum(hist.summary(scale=1e6, **ls)["sum"]
                     for ls in hist.label_sets())) / 1e6


def _bucket_misses(registry):
    ctr = registry.get("svgd_engine_bucket_misses_total")
    if ctr is None:
        return 0
    return int(sum(ctr.value(**ls) for ls in ctr.label_sets()))


def run_drill(tenants=DEFAULT_TENANTS, n_features=32, max_batch=64,
              requests=240, clients=2, ab_rounds=3, ab_requests=120,
              history_windows=4, seed=0):
    """The drill.  Returns the ``cost_attribution`` row (see
    :func:`row_ok` for the gates perf_regress applies to it)."""
    import jax

    from dist_svgd_tpu.telemetry import profile as _profile
    from dist_svgd_tpu.telemetry import usage as _usage
    from dist_svgd_tpu.telemetry.history import HistoryRecorder
    from tools.anomaly_report import analyze_records

    engines, batcher, registry = build_serving(
        tenants=tenants, n_features=n_features, max_batch=max_batch,
        seed=seed)
    _LAST_REGISTRY[0] = registry  # CLI --dump-metrics reads it back
    tenant_names = [name for name, _ in tenants]
    try:
        for eng in engines.values():
            eng.warmup()

        # fixed-size requests (= the single bucket) routed round-robin
        # across tenants: every dispatch is warm by construction
        pool_x = serve_bench._request_pool(
            n_features, rows_cycle=(max_batch,), pool=128, seed=seed + 1)
        pool = [(tenant_names[i % len(tenant_names)], x)
                for i, x in enumerate(pool_x)]

        def submit(item):
            tenant, x = item
            return batcher.submit(x, tenant=tenant)

        def run_window(nreq):
            return serve_bench.closed_loop(submit, pool, clients, nreq)

        run_window(max(2 * len(tenant_names), clients))  # settle the path

        # ---- A/B overhead: interleaved off/on rounds, best-of each arm
        best = {"off": 0.0, "on": 0.0}
        for _ in range(ab_rounds):
            off = run_window(ab_requests)
            _profile.enable_profiler(registry=registry)
            _usage.enable_usage(registry=registry)
            try:
                on = run_window(ab_requests)
            finally:
                _profile.disable_profiler()
                _usage.disable_usage()
            best["off"] = max(best["off"], off["rps"])
            best["on"] = max(best["on"], on["rps"])
        overhead = ((1.0 - best["on"] / best["off"])
                    if best["off"] > 0 else 0.0)

        # ---- the measured window: profiler + usage + sentry + history
        device_before = _measured_device_seconds(registry)
        attr_before = _profile.attributed_seconds(registry, "serve.")
        usage_before = _usage.usage_summary(registry)
        misses_before = _bucket_misses(registry)

        hist_dir = tempfile.mkdtemp(prefix="cost_drill_hist_")
        recorder = HistoryRecorder(registry, hist_dir, interval_s=0.0)
        _profile.enable_profiler(registry=registry)
        _usage.enable_usage(registry=registry)
        try:
            recorder.record_once()
            per_seg = max(requests // max(history_windows, 1), 1)
            segments = []
            with retrace_sentry("cost_drill.window") as sentry:
                for _ in range(max(history_windows, 1)):
                    segments.append(run_window(per_seg))
                    recorder.record_once()
        finally:
            _profile.disable_profiler()
            _usage.disable_usage()

        device_s = _measured_device_seconds(registry) - device_before
        attributed_s = (_profile.attributed_seconds(registry, "serve.")
                        - attr_before)
        coverage = attributed_s / device_s if device_s > 0 else 0.0

        usage_after = _usage.usage_summary(registry)
        tenant_device = {}
        compiles = 0
        for name, row in usage_after["tenants"].items():
            before = usage_before["tenants"].get(name, {})
            tenant_device[name] = (row["device_seconds"]
                                   - before.get("device_seconds", 0.0))
            compiles += row["compiles"] - before.get("compiles", 0)
        tenant_sum = sum(tenant_device.values())
        sum_err = (abs(tenant_sum - device_s) / device_s
                   if device_s > 0 else 1.0)

        history_records = recorder.history.records()
        anomalies = analyze_records(history_records, rate=True,
                                    min_segment=2)
        shutil.rmtree(hist_dir, ignore_errors=True)

        completed = sum(s["completed"] for s in segments)
        wall = sum(s["wall_s"] for s in segments)
        top = sorted(_profile.summary(registry, "serve.").items(),
                     key=lambda kv: -kv[1]["seconds"])[:5]
        return {
            "metric": "cost_attribution",
            "unit": "fraction of measured dispatch wall attributed",
            "value": round(coverage, 4),
            "coverage": round(coverage, 4),
            "attributed_s": round(attributed_s, 4),
            "measured_device_s": round(device_s, 4),
            "tenant_device_s": {k: round(v, 4)
                                for k, v in sorted(tenant_device.items())},
            "tenant_sum_err_frac": round(sum_err, 6),
            "recompiles": int(compiles
                              + (_bucket_misses(registry) - misses_before)),
            "sentry_compiles": sentry.compiles,
            "sentry_supported": sentry.supported,
            "profiler_overhead_frac": round(overhead, 4),
            "rps_disabled": round(best["off"], 1),
            "rps_enabled": round(best["on"], 1),
            "ab_rounds": ab_rounds,
            "requests": completed,
            "rps": round(completed / wall, 1) if wall > 0 else 0.0,
            "history_records": len(history_records),
            "history_anomalies": len(anomalies["anomalies"]),
            "top_programs": [
                {"label": label, **{k: round(v, 4) if isinstance(v, float)
                                    else v for k, v in row.items()}}
                for label, row in top],
            "tenants": len(tenant_names),
            "clients": clients,
            "max_batch": max_batch,
            "n_features": n_features,
            "platform": jax.default_backend(),
        }
    finally:
        batcher.close()


def row_ok(row):
    """The unconditional gates perf_regress applies to the row (the
    profiler-overhead ceiling is its own fixed-ceiling row there)."""
    why = []
    if row["coverage"] < COVERAGE_MIN:
        why.append(f"attribution coverage {row['coverage']:.3f} < "
                   f"{COVERAGE_MIN} of measured dispatch wall")
    if row["tenant_sum_err_frac"] > TENANT_SUM_TOL:
        why.append(f"per-tenant device-seconds sum off by "
                   f"{row['tenant_sum_err_frac']:.4f} > {TENANT_SUM_TOL} "
                   f"of total")
    if row["recompiles"] > 0:
        why.append(f"{row['recompiles']} in-window recompile(s) "
                   f"(kernel-cache misses / usage compile counts)")
    if row["sentry_supported"] and row["sentry_compiles"] > 0:
        why.append(f"retrace sentry counted {row['sentry_compiles']} "
                   f"XLA compile(s) in the steady-state window")
    return (not why, why)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=240)
    ap.add_argument("--clients", type=int, default=2)
    ap.add_argument("--ab-rounds", type=int, default=3)
    ap.add_argument("--ab-requests", type=int, default=120)
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--dump-metrics", default=None, metavar="PATH",
                    help="also save the drill registry's dump here "
                         "(feed it to trace_report --programs)")
    args = ap.parse_args(argv)

    row = run_drill(requests=args.requests, clients=args.clients,
                    ab_rounds=args.ab_rounds, ab_requests=args.ab_requests,
                    max_batch=args.max_batch)
    ok, why = row_ok(row)
    if args.dump_metrics and _LAST_REGISTRY[0] is not None:
        with open(args.dump_metrics, "w") as fh:
            json.dump(_LAST_REGISTRY[0].dump(), fh)
    if args.json:
        print(json.dumps({**row, "ok": ok, "why": why}))
    else:
        print(json.dumps(row, indent=2))
        if ok:
            print(f"cost_attribution OK: coverage {row['coverage']:.3f}, "
                  f"tenant-sum err {row['tenant_sum_err_frac']:.4f}, "
                  f"{row['recompiles']} recompiles, overhead "
                  f"{row['profiler_overhead_frac']:.4f}")
        else:
            print("cost_attribution FAIL: " + "; ".join(why))
    return 0 if ok else 1


#: The last drill's registry (CLI --dump-metrics); run_drill stores it.
_LAST_REGISTRY = [None]


if __name__ == "__main__":
    sys.exit(main())
