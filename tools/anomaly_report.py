"""Change-point anomaly report over a telemetry history ring.

Reads the ``telemetry_<seq>.json`` records a
:class:`~dist_svgd_tpu.telemetry.history.HistoryRecorder` wrote (window
deltas of one metrics registry) and scans every recorded series for a
**step change**, using the same robust noise model ``tools/
perf_regress.py`` judges BENCH rows with: median + MAD (median absolute
deviation), not mean + stddev, so a single outlier window neither
triggers nor masks a verdict.

Detection, per series: for every candidate split point ``t`` (leaving at
least ``--min-segment`` windows on each side), compare the medians of
the left and right segments.  A split is anomalous when::

    |median_right - median_left| > max(k * MAD_left,
                                       rel_floor * |median_left|,
                                       abs_floor)

i.e. the level shift must clear both the observed noise of the
*baseline* segment (``k`` MADs — ``k`` defaults to 6, twice
perf_regress's 3-MAD band, because an unattended report should page on
step changes, not tail noise) and a relative floor (a perfectly quiet
series has MAD 0; without the floor any epsilon would flag).  The
reported split is the one with the highest ratio of shift to threshold.
Everything is rank/median arithmetic on recorded values — **verdicts on
a fixed history are deterministic**, which is what lets the fixture
tests pin "flags the injected step, silent on clean".

Series values per window: counters and gauges use the recorded value
(counters are window deltas — pass ``--rate`` to normalise by each
record's ``interval_s``, skipping the first cumulative record);
histograms use the per-window mean by default (``--stat p99`` etc. for
quantiles reconstructed from the raw bucket counts).

Usage::

    python tools/anomaly_report.py DIR                 # scan everything
    python tools/anomaly_report.py DIR --json
    python tools/anomaly_report.py DIR --metric svgd_serve_request_latency_seconds --stat p99
    python tools/anomaly_report.py DIR --rate --k 8

Exit codes: 0 clean, 1 anomalies found, 2 unreadable input — shell-
gateable like the other tools.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dist_svgd_tpu.telemetry.history import (  # noqa: E402
    TelemetryHistory,
    list_series,
    series_values,
)

#: Baseline-noise multiplier (MADs) a level shift must clear.
DEFAULT_K = 6.0
#: Relative floor: shifts under this fraction of the baseline median
#: never flag (guards the MAD=0 quiet-series case).
DEFAULT_REL_FLOOR = 0.25
#: Minimum windows on each side of a candidate split.
DEFAULT_MIN_SEGMENT = 4


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _mad(vals: List[float], med: Optional[float] = None) -> float:
    if med is None:
        med = _median(vals)
    return _median([abs(v - med) for v in vals])


def detect_step_change(values: List[float], *, k: float = DEFAULT_K,
                       min_segment: int = DEFAULT_MIN_SEGMENT,
                       rel_floor: float = DEFAULT_REL_FLOOR,
                       abs_floor: float = 0.0) -> Optional[Dict[str, Any]]:
    """Scan one series for its strongest step change; ``None`` when no
    split clears the threshold.  Deterministic in ``values``."""
    n = len(values)
    if n < 2 * min_segment:
        return None
    best: Optional[Dict[str, Any]] = None
    for t in range(min_segment, n - min_segment + 1):
        left, right = values[:t], values[t:]
        ml, mr = _median(left), _median(right)
        threshold = max(k * _mad(left, ml), rel_floor * abs(ml), abs_floor)
        if threshold <= 0.0:
            continue
        shift = abs(mr - ml)
        score = shift / threshold
        if score > 1.0 and (best is None or score > best["score"]):
            best = {
                "split_index": t,
                "median_before": ml,
                "median_after": mr,
                "shift": mr - ml,
                "threshold": threshold,
                "score": round(score, 3),
            }
    return best


def analyze_records(records: List[dict], *, metric: Optional[str] = None,
                    stat: Optional[str] = None, rate: bool = False,
                    k: float = DEFAULT_K,
                    min_segment: int = DEFAULT_MIN_SEGMENT,
                    rel_floor: float = DEFAULT_REL_FLOOR,
                    abs_floor: float = 0.0) -> Dict[str, Any]:
    """Run detection over every (or one ``metric``'s) recorded series.
    Returns ``{"windows": n, "series_scanned": n, "anomalies": [...]}``
    with anomalies sorted strongest first."""
    anomalies: List[Dict[str, Any]] = []
    scanned = 0
    for name, kind, labels in list_series(records):
        if metric is not None and name != metric:
            continue
        use_stat = stat if kind == "histogram" else None
        vals = series_values(records, name, labels=labels, stat=use_stat)
        series: List[float] = []
        for rec, v in zip(records, vals):
            if v is None:
                continue
            if rate and kind == "counter":
                interval = float(rec.get("interval_s", 0.0) or 0.0)
                if interval <= 0.0:
                    continue  # the first cumulative record has no window
                v = v / interval
            series.append(float(v))
        if len(series) < 2 * min_segment:
            continue
        scanned += 1
        hit = detect_step_change(series, k=k, min_segment=min_segment,
                                 rel_floor=rel_floor, abs_floor=abs_floor)
        if hit is not None:
            anomalies.append({
                "metric": name, "kind": kind, "labels": labels,
                "stat": use_stat or ("rate" if rate and kind == "counter"
                                     else "value"),
                "windows": len(series), **hit,
            })
    anomalies.sort(key=lambda a: -a["score"])
    return {"windows": len(records), "series_scanned": scanned,
            "anomalies": anomalies}


def render(report: Dict[str, Any]) -> str:
    out = [f"anomaly report: {report['windows']} windows, "
           f"{report['series_scanned']} series scanned, "
           f"{len(report['anomalies'])} anomalies"]
    for a in report["anomalies"]:
        labels = ",".join(f"{k}={v}" for k, v in sorted(a["labels"].items()))
        name = a["metric"] + (f"{{{labels}}}" if labels else "")
        out.append(
            f"  {name} [{a['stat']}] window {a['split_index']}: "
            f"{a['median_before']:.6g} -> {a['median_after']:.6g} "
            f"(shift {a['shift']:+.6g}, {a['score']}x threshold)")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("history", help="telemetry history directory "
                                    "(telemetry_<seq>.json records)")
    ap.add_argument("--metric", default=None,
                    help="scan only this metric (default: every series)")
    ap.add_argument("--stat", default=None,
                    help="histogram statistic: count, sum, mean (default), "
                         "p50/p95/p99")
    ap.add_argument("--rate", action="store_true",
                    help="normalise counter windows by interval_s "
                         "(skips the first cumulative record)")
    ap.add_argument("--k", type=float, default=DEFAULT_K,
                    help="MADs of baseline noise a shift must clear")
    ap.add_argument("--min-segment", type=int, default=DEFAULT_MIN_SEGMENT,
                    help="minimum windows on each side of a split")
    ap.add_argument("--rel-floor", type=float, default=DEFAULT_REL_FLOOR,
                    help="minimum shift as a fraction of baseline median")
    ap.add_argument("--abs-floor", type=float, default=0.0,
                    help="minimum absolute shift")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.history):
        print(f"anomaly_report: not a directory: {args.history}",
              file=sys.stderr)
        return 2
    records = TelemetryHistory(args.history).records()
    if not records:
        print(f"anomaly_report: no telemetry records under {args.history}",
              file=sys.stderr)
        return 2
    report = analyze_records(
        records, metric=args.metric, stat=args.stat, rate=args.rate,
        k=args.k, min_segment=args.min_segment, rel_floor=args.rel_floor,
        abs_floor=args.abs_floor)
    print(json.dumps(report) if args.json else render(report))
    return 1 if report["anomalies"] else 0


if __name__ == "__main__":
    sys.exit(main())
