"""Fleet failover drill: availability through replica loss, partition,
and readmission — the ``fleet_failover`` bench row.

The drill runs steady open-loop load through a
:class:`~dist_svgd_tpu.serving.fleet.FleetRouter` over 3 replicas and
walks the failure story end to end:

1. **steady** — baseline latency with everyone healthy;
2. **kill** — one replica dies mid-load (fake: transport ``kill``; real:
   ``SIGKILL`` on the subprocess).  Every in-flight and subsequent
   request must be absorbed by retries/failover: the row counts any
   **lost (non-shed) request as an unconditional FAIL** in
   ``perf_regress``.  Detection latency = kill → circuit-open, read off
   the replica set's transition log;
3. **partition** — a second replica becomes unreachable *from the router*
   while staying alive (fake: ``partition``; real: the
   :class:`~dist_svgd_tpu.serving.fleet.HttpTransport` deny-list — the
   subprocess keeps running untouched).  Same ejection path as a crash,
   zero replica-side effects; the row records p99 during the partition
   window;
4. **restart** — the killed replica comes back and must be re-admitted
   through the half-open circuit; time-to-readmit = restore →
   circuit-closed.

Modes:

- ``--mode fake`` (default) — :class:`LoopbackReplica` +
  :class:`FakeTransport`: no sockets, no jax, runs in tier-1
  (``tests/test_fleet_drill.py`` pins the row schema and the zero-lost
  contract);
- ``--mode real`` — 3 ``PredictionServer`` subprocesses
  (``JAX_PLATFORMS=cpu`` — the drill measures the router, not the chip)
  serving a real logreg checkpoint over real sockets, kill/partition/
  restart for real.  Slow-marked in the test suite.

Cross-process observability (round 16): the drill additionally measures
the fleet's **trace stitching** and **metrics federation**.  In fake mode
each replica owns its own tracer + metrics registry (standing in for a
separate process), the router traces its route trees, every export lands
in a temp dir and ``tools/trace_report.py --stitch`` joins them —
``trace_stitch_coverage`` must be **1.0** (every non-shed served request
reassembles into exactly one router→replica tree; ``perf_regress`` FAILs
otherwise) and the kill phase's retries must appear as sibling attempts
(``stitch_retry_trees >= 1``).  A restart installs a FRESH replica
(registry reset to zero), so the federation's counter-reset clamping is
exercised in-drill: ``federation_monotone`` must stay True.  In real mode
the federation runs over real sockets too (``federation_scrape_ms`` is a
real scrape wall), but ``trace_stitch_coverage`` is ``null`` — a
SIGKILLed replica takes its in-memory trace buffer with it, which is
exactly why the streamed-export fake drill carries the stitch gate.

Row fields are documented in ``tools/README.md``;
``tools/perf_regress.py`` gates ``detect_s`` / ``readmit_s`` /
``federation_scrape_ms`` with median+MAD incumbent windows and FAILs
unconditionally on ``lost_requests > 0``, ``misroutes > 0`` (a routed
request reaching an ejected replica), fake-mode stitch coverage below
1.0, or a non-monotone federated counter.

Usage::

    python tools/fleet_drill.py                 # fake-mode row
    python tools/fleet_drill.py --mode real     # subprocess drill
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import trace_report

from dist_svgd_tpu import telemetry
from dist_svgd_tpu.resilience.backoff import Backoff
from dist_svgd_tpu.serving import fleet as fleet_mod
from dist_svgd_tpu.telemetry.metrics import MetricsRegistry
from dist_svgd_tpu.telemetry.trace import Tracer

REPLICAS = ("r0", "r1", "r2")
TENANTS = tuple(f"t{i}" for i in range(8))


def _p99(vals: List[float]) -> float:
    if not vals:
        return 0.0
    vals = sorted(vals)
    return vals[min(len(vals) - 1, int(round(0.99 * (len(vals) - 1))))]


class _OpenLoopLoad:
    """Open-loop request generator: fires at ``rate_hz`` regardless of
    completions (the arrival process a real fleet sees), tagging each
    record with the drill phase active at submit time."""

    def __init__(self, router, rate_hz: float, workers: int = 32,
                 tenant_in_body: bool = True):
        self._router = router
        self._rate = rate_hz
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="drill-load")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.phase = ["warmup"]  # single-slot mutable cell
        self.records: List[Tuple[str, int, float]] = []  # (phase, status, s)
        self._tenant_i = 0
        # the routing key is always the tenant; single-tenant
        # PredictionServer replicas reject a "tenant" body field, so the
        # real drill keeps it out of the payload
        self._tenant_in_body = tenant_in_body

    def _one(self, tenant: str, phase: str) -> None:
        doc = {"inputs": [[0.1, 0.2]]}
        if self._tenant_in_body:
            doc["tenant"] = tenant
        body = json.dumps(doc).encode()
        t0 = time.monotonic()
        res = self._router.route(tenant, body)
        self.records.append((phase, res.status, time.monotonic() - t0))

    def _loop(self) -> None:
        interval = 1.0 / self._rate
        t_next = time.monotonic()
        while not self._stop.is_set():
            tenant = TENANTS[self._tenant_i % len(TENANTS)]
            self._tenant_i += 1
            self._pool.submit(self._one, tenant, self.phase[0])
            t_next += interval
            delay = t_next - time.monotonic()
            if delay > 0:
                time.sleep(delay)

    def start(self) -> "_OpenLoopLoad":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._pool.shutdown(wait=True)

    def counts(self, phase: str) -> Dict[str, Any]:
        rows = [r for r in self.records if r[0] == phase]
        lost = sum(1 for _, s, _ in rows if s >= 500)
        shed = sum(1 for _, s, _ in rows if s == 429)
        ok = sum(1 for _, s, _ in rows if 200 <= s < 300)
        lat = [w for _, s, w in rows if 200 <= s < 300]
        return {"total": len(rows), "ok": ok, "lost": lost, "shed": shed,
                "p99_ms": round(_p99(lat) * 1e3, 3)}


def _transition_ts(replica_set, rid: str, to_state: str,
                   after_ts: float) -> Optional[float]:
    for ts, r, _frm, to, _reason in list(replica_set.state_changes):
        if r == rid and to == to_state and ts >= after_ts:
            return ts
    return None


# --------------------------------------------------------------------- #
# replica backends


class _FakeFleet:
    """3 LoopbackReplicas on a FakeTransport; faults are transport flips.

    Each replica owns its OWN tracer and metrics registry — the
    in-process stand-in for separate replica processes, so the drill can
    exercise cross-process stitching and federation without sockets.  A
    ``restart`` installs a **fresh** replica (counters back at zero, new
    tracer): exactly the reset the federation must clamp.  Every
    generation's tracer is kept for export — modelling replicas that
    stream their JSONL exports off-process (the reason fake mode can
    stitch through a kill while real mode cannot)."""

    def __init__(self, trace: bool = True):
        self._trace = trace
        self.generations: List[Tuple[str, Tracer]] = []
        self.replicas: Dict[str, fleet_mod.LoopbackReplica] = {}
        self.transport = fleet_mod.FakeTransport({})
        for rid in REPLICAS:
            self.replicas[rid] = self._make_replica(rid)
            self.transport.set_replica(rid, self.replicas[rid])

    def _make_replica(self, rid):
        tracer = None
        if self._trace:
            tracer = Tracer(registry=MetricsRegistry())
            tracer.set_process("replica", rid)
            self.generations.append((rid, tracer))
        return fleet_mod.LoopbackReplica(
            rid, predict_fn=self._predict, tenants=TENANTS,
            registry=MetricsRegistry(), tracer=tracer)

    @staticmethod
    def _predict(inputs, tenant, headers):
        time.sleep(0.001)  # a realistic (tiny) dispatch floor
        return {"mean": [0.0] * len(inputs)}

    def kill(self, rid):
        self.transport.kill(rid)

    def partition(self, rid):
        self.transport.partition(rid)

    def heal(self, rid):
        self.transport.restore(rid)

    def restart(self, rid):
        # a restarted process comes back EMPTY: fresh registry (counter
        # reset → federation clamp) and fresh tracer (new epoch/anchor)
        self.replicas[rid] = self._make_replica(rid)
        self.transport.set_replica(rid, self.replicas[rid])
        self.transport.restore(rid)

    def close(self):
        pass

    def export_traces(self, outdir: str) -> List[str]:
        """One Chrome export per replica generation (r0 may have two:
        pre-kill and post-restart)."""
        paths = []
        counts: Dict[str, int] = {}
        for rid, tracer in self.generations:
            gen = counts.get(rid, 0)
            counts[rid] = gen + 1
            path = os.path.join(outdir, f"{rid}-gen{gen}.json")
            tracer.export_chrome(path)
            paths.append(path)
        return paths

    def assert_partition_clean(self, rid) -> Dict[str, Any]:
        """The partitioned replica must be ALIVE: reachable directly (not
        through the router's cut) and with zero flight-recorder trips."""
        rep = self.replicas[rid]
        reply = rep.handle("GET", "/healthz", None, {})
        return {"alive": reply.status == 200,
                "flight_trips": rep.flight_trips,
                "served_during_partition": rep.requests}


class _RealFleet:
    """3 PredictionServer subprocesses over real sockets (CPU jax)."""

    def __init__(self, tmpdir: str, max_batch: int = 16):
        import socket
        import subprocess

        import numpy as np

        from dist_svgd_tpu.utils.checkpoint import save_state

        self._subprocess = subprocess
        ckpt = os.path.join(tmpdir, "ckpt")
        rng = np.random.default_rng(0)
        save_state(ckpt, {"particles": rng.normal(
            size=(64, 3)).astype(np.float32), "t": 1}, backend="npz")
        self._ckpt = ckpt
        self._max_batch = max_batch
        self.addresses: Dict[str, Tuple[str, int]] = {}
        self._procs: Dict[str, Any] = {}
        for rid in REPLICAS:
            with socket.socket() as s:  # grab a free port per replica
                s.bind(("127.0.0.1", 0))
                self.addresses[rid] = ("127.0.0.1", s.getsockname()[1])
        self.transport = fleet_mod.HttpTransport(self.addresses)
        for rid in REPLICAS:
            self._spawn(rid)
        for rid in REPLICAS:
            self._wait_healthy(rid)

    def _spawn(self, rid: str) -> None:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        host, port = self.addresses[rid]
        self._procs[rid] = self._subprocess.Popen(
            [sys.executable, "-m", "dist_svgd_tpu.serving.server",
             "--checkpoint", self._ckpt, "--model", "logreg",
             "--host", host, "--port", str(port),
             "--max-batch", str(self._max_batch), "--max-wait-ms", "1.0"],
            env=env, stdout=self._subprocess.DEVNULL,
            stderr=self._subprocess.DEVNULL,
        )

    def _wait_healthy(self, rid: str, timeout_s: float = 60.0) -> None:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            try:
                reply = self.transport.request(rid, "GET", "/healthz",
                                               timeout_s=1.0)
                if reply.status == 200:
                    return
            except fleet_mod.TransportError:
                pass
            time.sleep(0.2)
        raise RuntimeError(f"replica {rid} never became healthy")

    def kill(self, rid):
        self._procs[rid].kill()
        self._procs[rid].wait(timeout=10)

    def partition(self, rid):
        self.transport.partition(rid)

    def heal(self, rid):
        self.transport.heal(rid)

    def restart(self, rid):
        self._spawn(rid)
        self._wait_healthy(rid)

    def close(self):
        for p in self._procs.values():
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:
                pass

    def assert_partition_clean(self, rid) -> Dict[str, Any]:
        """Bypass the router-side cut: a direct probe (fresh transport, no
        deny-list) must still see a live, healthy process."""
        direct = fleet_mod.HttpTransport(self.addresses)
        try:
            reply = direct.request(rid, "GET", "/healthz", timeout_s=2.0)
            return {"alive": reply.status == 200, "flight_trips": 0,
                    "served_during_partition": None}
        except fleet_mod.TransportError:
            return {"alive": False, "flight_trips": None,
                    "served_during_partition": None}


# --------------------------------------------------------------------- #


def run_drill(mode: str = "fake", *, rate_hz: float = 200.0,
              steady_s: float = 0.6, kill_s: float = 0.8,
              partition_s: float = 0.8, probe_interval_s: float = 0.05,
              open_cooldown_s: float = 0.25,
              readmit_timeout_s: float = 10.0,
              hedge: bool = False, trace: bool = True) -> Dict[str, Any]:
    """Run the drill, return the ``fleet_failover`` row dict.

    ``trace`` (fake mode) enables the router-side tracer and the replica
    stand-in tracers, exports every process's trace to a temp dir, and
    stitches them (``trace_report.stitch_files``) into the
    ``trace_stitch_coverage`` / ``stitch_retry_trees`` row fields.  Real
    mode never stitches (a SIGKILL takes the replica's in-memory trace
    buffer with it) — coverage reads ``null`` there."""
    if mode not in ("fake", "real"):
        raise ValueError(f"mode must be fake|real, got {mode!r}")
    registry = MetricsRegistry()
    stitch = mode == "fake" and trace
    router_tracer = None
    own_tracer = False
    trace_t0_us = 0.0
    prev_process = None
    if stitch:
        own_tracer = telemetry.get_tracer() is None
        router_tracer = telemetry.enable(registry=registry)
        # a BORROWED outer tracer (perf_regress composing tools) gets its
        # identity back afterwards — this process is only "the router"
        # for the drill's duration
        prev_process = (None if own_tracer
                        else router_tracer.process_meta())
        router_tracer.set_process("router", "router")
        # an outer tracer may carry spans from earlier benches: stitch
        # only what THIS drill routes
        trace_t0_us = router_tracer.now() * 1e6
    try:
        return _drill_body(
            mode, stitch=stitch, router_tracer=router_tracer,
            trace_t0_us=trace_t0_us, registry=registry, rate_hz=rate_hz,
            steady_s=steady_s, kill_s=kill_s, partition_s=partition_s,
            probe_interval_s=probe_interval_s,
            open_cooldown_s=open_cooldown_s,
            readmit_timeout_s=readmit_timeout_s, hedge=hedge)
    finally:
        # tracer cleanup on EVERY exit path — a drill aborting mid-phase
        # must not leave the process-global tracer installed (it would
        # silently trace every later bench in this process) or a
        # borrowed one mislabelled as the router
        if stitch:
            if own_tracer:
                telemetry.disable()
            elif prev_process is not None:
                router_tracer.set_process(prev_process["role"],
                                          prev_process["name"])


def _drill_body(mode, *, stitch, router_tracer, trace_t0_us, registry,
                rate_hz, steady_s, kill_s, partition_s, probe_interval_s,
                open_cooldown_s, readmit_timeout_s, hedge):
    tmpdir = None
    if mode == "real":
        tmpdir = tempfile.TemporaryDirectory(prefix="fleet_drill_")
        backend = _RealFleet(tmpdir.name)
        probe_interval_s = max(probe_interval_s, 0.1)
    else:
        backend = _FakeFleet(trace=stitch)
    t_wall0 = time.monotonic()
    replica_set = fleet_mod.ReplicaSet(
        REPLICAS, backend.transport,
        probe_interval_s=probe_interval_s,
        probe_timeout_s=0.5 if mode == "real" else 0.2,
        fail_threshold=2, passive_fail_threshold=2,
        open_cooldown_s=open_cooldown_s,
        registry=registry,
    )
    router = fleet_mod.FleetRouter(
        list(REPLICAS), transport=backend.transport,
        replica_set=replica_set,
        max_retries=2, per_try_timeout_s=1.0 if mode == "real" else 0.5,
        default_deadline_s=5.0,
        backoff=Backoff(base_s=0.005, factor=2.0, max_s=0.05,
                        jitter_frac=0.2),
        hedge=hedge, registry=registry,
        # real mode shares 2 cores between 3 jax replicas, the router,
        # and the load generator: a scrape must never stall a sweep for
        # a full second behind one busy replica
        federation_timeout_s=0.5 if mode == "real" else 1.0,
    )
    router.start()
    load = _OpenLoopLoad(router, rate_hz,
                         tenant_in_body=mode == "fake").start()
    partition_clean = None
    federation = router.federation
    try:
        # Federation sweeps run MID-phase, never at a phase boundary: a
        # sweep costs real CPU in the drill process (3 scrapes + dump
        # merge) and on this 2-core box a boundary sweep lands exactly on
        # the kill/partition instant — enough perturbation to tip the
        # (deliberately tight) real-mode fleet into an ejection cascade
        # that the drill would then mis-attribute to the router.

        # phase 1: steady state
        load.phase[0] = "steady"
        time.sleep(steady_s / 2)
        federation.scrape_once()  # everyone alive: the exactness sweep
        time.sleep(steady_s / 2)

        # phase 2: kill r0 under load — retries must absorb every request
        load.phase[0] = "kill"
        t_kill = time.monotonic()
        backend.kill("r0")
        time.sleep(kill_s / 2)
        # the dead replica's scrape FAILS and is counted — federation
        # degrades visibly, the survivors keep federating
        federation.scrape_once()
        time.sleep(kill_s / 2)
        ts_open = _transition_ts(replica_set, "r0", "open", t_kill)
        detect_s = None if ts_open is None else ts_open - t_kill

        # phase 3: partition r1 (alive, unreachable) — same ejection path
        load.phase[0] = "partition"
        t_part = time.monotonic()
        backend.partition("r1")
        time.sleep(partition_s / 2)
        federation.scrape_once()
        time.sleep(partition_s / 2)
        partition_clean = backend.assert_partition_clean("r1")
        backend.heal("r1")

        # phase 4: restart r0 — must come back through half-open
        load.phase[0] = "restart"
        t_restart = time.monotonic()
        backend.restart("r0")
        deadline = time.monotonic() + readmit_timeout_s
        ts_closed = None
        while time.monotonic() < deadline:
            ts_closed = _transition_ts(replica_set, "r0", "closed", t_restart)
            if ts_closed is not None:
                break
            time.sleep(probe_interval_s / 2)
        readmit_s = None if ts_closed is None else ts_closed - t_restart
        load.phase[0] = "cooldown"
        # the restarted replica reports RESET counters: the clamped delta
        # must keep every federated rollup monotone
        federation.scrape_once()
    finally:
        load.stop()
        router.shutdown()
        backend.close()
        if tmpdir is not None:
            tmpdir.cleanup()

    # ---- trace stitch (fake mode): every served route must reassemble
    # into one router→replica tree on its X-Fleet-Trace id
    stitch_report = None
    if stitch:
        with tempfile.TemporaryDirectory(prefix="fleet_stitch_") as sd:
            router_path = os.path.join(sd, "router.json")
            events = [e for e in router_tracer.chrome_events()
                      if e.get("ph") == "M"
                      or e.get("ts", 0.0) >= trace_t0_us - 1.0]
            with open(router_path, "w") as fh:
                json.dump({"traceEvents": events,
                           "displayTimeUnit": "ms",
                           "otherData": {
                               "process": router_tracer.process_meta()}},
                          fh)
            replica_paths = backend.export_traces(sd)
            stitch_report = trace_report.stitch_files(
                [router_path] + replica_paths)

    steady = load.counts("steady")
    kill = load.counts("kill")
    part = load.counts("partition")
    restart = load.counts("restart")
    total = steady["total"] + kill["total"] + part["total"] + restart["total"]
    lost = (steady["lost"] + kill["lost"] + part["lost"] + restart["lost"])
    shed = (steady["shed"] + kill["shed"] + part["shed"] + restart["shed"])
    availability = (1.0 if kill["total"] == 0
                    else kill["ok"] / max(kill["total"] - kill["shed"], 1))

    def _counter_sum(name: str) -> float:
        metric = registry._metrics.get(name)
        if metric is None:
            return 0
        with metric._lock:
            return sum(metric._series.values())

    def _fed_requests_total() -> float:
        """The federated request rollup: every non-replica-labelled
        series summed (the per-tenant rollups partition the total)."""
        metric = federation.fleet_registry.get("svgd_serve_requests_total")
        if metric is None:
            return 0.0
        return float(sum(metric.value(**ls) for ls in metric.label_sets()
                         if "replica" not in ls))

    def _scrape_ms(reg) -> Optional[float]:
        """Median federation sweep wall (ms) off the scrape histogram —
        robust to the one slow sweep a phase transition can catch."""
        hist = reg.get("svgd_fleet_scrape_seconds")
        if hist is None or not hist.summary()["count"]:
            return None
        return round(hist.quantile(0.5) * 1e3, 3)

    row = {
        "metric": "fleet_failover",
        "value": round(availability, 6),
        "unit": "non-shed availability during single-replica loss",
        "mode": mode,
        "replicas": len(REPLICAS),
        "rate_hz": rate_hz,
        "requests": total,
        "lost_requests": lost,
        "shed_requests": shed,
        "detect_s": None if detect_s is None else round(detect_s, 4),
        "detect_probe_intervals": (
            None if detect_s is None
            else round(detect_s / probe_interval_s, 2)),
        "readmit_s": None if readmit_s is None else round(readmit_s, 4),
        "p99_steady_ms": steady["p99_ms"],
        "p99_kill_ms": kill["p99_ms"],
        "p99_partition_ms": part["p99_ms"],
        "retries": int(_counter_sum("svgd_fleet_retries_total")),
        "hedges": int(_counter_sum("svgd_fleet_hedges_total")),
        "failovers": int(_counter_sum("svgd_fleet_failovers_total")),
        "misroutes": int(_counter_sum("svgd_fleet_misroutes_total")),
        "ejections": int(_counter_sum("svgd_fleet_ejections_total")),
        "readmissions": int(_counter_sum("svgd_fleet_readmissions_total")),
        "partition_replica_alive": (
            None if partition_clean is None else partition_clean["alive"]),
        "partition_flight_trips": (
            None if partition_clean is None
            else partition_clean["flight_trips"]),
        # cross-process observability (round 16)
        "trace_stitch_coverage": (
            None if stitch_report is None else stitch_report["coverage"]),
        "stitch_served_routes": (
            None if stitch_report is None
            else stitch_report["served_routes"]),
        "stitch_retry_trees": (
            None if stitch_report is None
            else stitch_report["retry_trees"]),
        "stitch_orphans": (
            None if stitch_report is None
            else stitch_report["orphan_replica_traces"]),
        "federation_scrape_ms": _scrape_ms(registry),
        "federation_scrapes": federation.scrapes,
        "federation_scrapes_skipped": federation.skips,
        "federation_scrape_errors": int(
            _counter_sum("svgd_fleet_scrape_errors_total")),
        "federation_monotone": federation.monotone,
        "federated_requests_total": _fed_requests_total(),
        "probe_interval_s": probe_interval_s,
        "open_cooldown_s": open_cooldown_s,
        "status_counts": {
            str(s): sum(1 for _, st, _ in load.records if st == s)
            for s in sorted({st for _, st, _ in load.records})},
        "wall_s": round(time.monotonic() - t_wall0, 3),
    }
    return row


def row_ok(row: Dict[str, Any]) -> Tuple[bool, List[str]]:
    """The unconditional correctness gates ``perf_regress`` applies to a
    ``fleet_failover`` row (speed is windowed separately)."""
    why = []
    if row["lost_requests"] > 0:
        why.append(f"lost {row['lost_requests']} non-shed request(s) — "
                   "retries failed to absorb a replica loss")
    if row["misroutes"] > 0:
        why.append(f"{row['misroutes']} request(s) routed to an ejected "
                   "replica")
    if row["detect_s"] is None:
        why.append("the killed replica was never ejected")
    if row["readmit_s"] is None:
        why.append("the restarted replica was never re-admitted")
    if row["readmissions"] < 1:
        why.append("no half-open readmission observed")
    if row["partition_replica_alive"] is False:
        why.append("the partitioned replica died — partition must leave "
                   "the process untouched")
    if row["partition_flight_trips"] not in (None, 0):
        why.append("partition tripped the replica's own flight recorder")
    # cross-process observability gates (round 16).  Stitch coverage is a
    # fake-mode contract: replica traces there model streamed exports, so
    # EVERY served request must reassemble (real mode reads null — a
    # SIGKILLed process takes its trace buffer with it).
    if row.get("mode") == "fake":
        cov = row.get("trace_stitch_coverage")
        if cov is None or cov < 1.0:
            why.append(f"trace stitch coverage {cov} < 1.0 — some served "
                       "request's router and replica spans no longer join "
                       "on the trace id")
    if row.get("federation_monotone") is False:
        why.append("a federated counter rollup decreased across scrapes — "
                   "the restart clamp broke (negative rates)")
    return (not why), why


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("fake", "real"), default="fake")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="open-loop request rate (req/s)")
    ap.add_argument("--hedge", action="store_true",
                    help="enable tail hedging in the router under drill")
    args = ap.parse_args(argv)
    row = run_drill(mode=args.mode, rate_hz=args.rate, hedge=args.hedge)
    ok, why = row_ok(row)
    row["ok"] = ok
    if why:
        row["failures"] = why
    print(json.dumps(row), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
