"""Fleet failover drill: availability through replica loss, partition,
and readmission — the ``fleet_failover`` bench row.

The drill runs steady open-loop load through a
:class:`~dist_svgd_tpu.serving.fleet.FleetRouter` over 3 replicas and
walks the failure story end to end:

1. **steady** — baseline latency with everyone healthy;
2. **kill** — one replica dies mid-load (fake: transport ``kill``; real:
   ``SIGKILL`` on the subprocess).  Every in-flight and subsequent
   request must be absorbed by retries/failover: the row counts any
   **lost (non-shed) request as an unconditional FAIL** in
   ``perf_regress``.  Detection latency = kill → circuit-open, read off
   the replica set's transition log;
3. **partition** — a second replica becomes unreachable *from the router*
   while staying alive (fake: ``partition``; real: the
   :class:`~dist_svgd_tpu.serving.fleet.HttpTransport` deny-list — the
   subprocess keeps running untouched).  Same ejection path as a crash,
   zero replica-side effects; the row records p99 during the partition
   window;
4. **restart** — the killed replica comes back and must be re-admitted
   through the half-open circuit; time-to-readmit = restore →
   circuit-closed.

Modes:

- ``--mode fake`` (default) — :class:`LoopbackReplica` +
  :class:`FakeTransport`: no sockets, no jax, runs in tier-1
  (``tests/test_fleet_drill.py`` pins the row schema and the zero-lost
  contract);
- ``--mode real`` — 3 ``PredictionServer`` subprocesses
  (``JAX_PLATFORMS=cpu`` — the drill measures the router, not the chip)
  serving a real logreg checkpoint over real sockets, kill/partition/
  restart for real.  Slow-marked in the test suite.

Row fields are documented in ``tools/README.md``;
``tools/perf_regress.py`` gates ``detect_s`` / ``readmit_s`` with
median+MAD incumbent windows and FAILs unconditionally on
``lost_requests > 0`` or ``misroutes > 0`` (a routed request reaching an
ejected replica).

Usage::

    python tools/fleet_drill.py                 # fake-mode row
    python tools/fleet_drill.py --mode real     # subprocess drill
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dist_svgd_tpu.resilience.backoff import Backoff
from dist_svgd_tpu.serving import fleet as fleet_mod
from dist_svgd_tpu.telemetry.metrics import MetricsRegistry

REPLICAS = ("r0", "r1", "r2")
TENANTS = tuple(f"t{i}" for i in range(8))


def _p99(vals: List[float]) -> float:
    if not vals:
        return 0.0
    vals = sorted(vals)
    return vals[min(len(vals) - 1, int(round(0.99 * (len(vals) - 1))))]


class _OpenLoopLoad:
    """Open-loop request generator: fires at ``rate_hz`` regardless of
    completions (the arrival process a real fleet sees), tagging each
    record with the drill phase active at submit time."""

    def __init__(self, router, rate_hz: float, workers: int = 32,
                 tenant_in_body: bool = True):
        self._router = router
        self._rate = rate_hz
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="drill-load")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.phase = ["warmup"]  # single-slot mutable cell
        self.records: List[Tuple[str, int, float]] = []  # (phase, status, s)
        self._tenant_i = 0
        # the routing key is always the tenant; single-tenant
        # PredictionServer replicas reject a "tenant" body field, so the
        # real drill keeps it out of the payload
        self._tenant_in_body = tenant_in_body

    def _one(self, tenant: str, phase: str) -> None:
        doc = {"inputs": [[0.1, 0.2]]}
        if self._tenant_in_body:
            doc["tenant"] = tenant
        body = json.dumps(doc).encode()
        t0 = time.monotonic()
        res = self._router.route(tenant, body)
        self.records.append((phase, res.status, time.monotonic() - t0))

    def _loop(self) -> None:
        interval = 1.0 / self._rate
        t_next = time.monotonic()
        while not self._stop.is_set():
            tenant = TENANTS[self._tenant_i % len(TENANTS)]
            self._tenant_i += 1
            self._pool.submit(self._one, tenant, self.phase[0])
            t_next += interval
            delay = t_next - time.monotonic()
            if delay > 0:
                time.sleep(delay)

    def start(self) -> "_OpenLoopLoad":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._pool.shutdown(wait=True)

    def counts(self, phase: str) -> Dict[str, Any]:
        rows = [r for r in self.records if r[0] == phase]
        lost = sum(1 for _, s, _ in rows if s >= 500)
        shed = sum(1 for _, s, _ in rows if s == 429)
        ok = sum(1 for _, s, _ in rows if 200 <= s < 300)
        lat = [w for _, s, w in rows if 200 <= s < 300]
        return {"total": len(rows), "ok": ok, "lost": lost, "shed": shed,
                "p99_ms": round(_p99(lat) * 1e3, 3)}


def _transition_ts(replica_set, rid: str, to_state: str,
                   after_ts: float) -> Optional[float]:
    for ts, r, _frm, to, _reason in list(replica_set.state_changes):
        if r == rid and to == to_state and ts >= after_ts:
            return ts
    return None


# --------------------------------------------------------------------- #
# replica backends


class _FakeFleet:
    """3 LoopbackReplicas on a FakeTransport; faults are transport flips."""

    def __init__(self):
        self.replicas = {
            rid: fleet_mod.LoopbackReplica(
                rid, predict_fn=self._predict, tenants=TENANTS)
            for rid in REPLICAS
        }
        self.transport = fleet_mod.FakeTransport(self.replicas)

    @staticmethod
    def _predict(inputs, tenant, headers):
        time.sleep(0.001)  # a realistic (tiny) dispatch floor
        return {"mean": [0.0] * len(inputs)}

    def kill(self, rid):
        self.transport.kill(rid)

    def partition(self, rid):
        self.transport.partition(rid)

    def heal(self, rid):
        self.transport.restore(rid)

    def restart(self, rid):
        self.transport.restore(rid)

    def close(self):
        pass

    def assert_partition_clean(self, rid) -> Dict[str, Any]:
        """The partitioned replica must be ALIVE: reachable directly (not
        through the router's cut) and with zero flight-recorder trips."""
        rep = self.replicas[rid]
        reply = rep.handle("GET", "/healthz", None, {})
        return {"alive": reply.status == 200,
                "flight_trips": rep.flight_trips,
                "served_during_partition": rep.requests}


class _RealFleet:
    """3 PredictionServer subprocesses over real sockets (CPU jax)."""

    def __init__(self, tmpdir: str, max_batch: int = 16):
        import socket
        import subprocess

        import numpy as np

        from dist_svgd_tpu.utils.checkpoint import save_state

        self._subprocess = subprocess
        ckpt = os.path.join(tmpdir, "ckpt")
        rng = np.random.default_rng(0)
        save_state(ckpt, {"particles": rng.normal(
            size=(64, 3)).astype(np.float32), "t": 1}, backend="npz")
        self._ckpt = ckpt
        self._max_batch = max_batch
        self.addresses: Dict[str, Tuple[str, int]] = {}
        self._procs: Dict[str, Any] = {}
        for rid in REPLICAS:
            with socket.socket() as s:  # grab a free port per replica
                s.bind(("127.0.0.1", 0))
                self.addresses[rid] = ("127.0.0.1", s.getsockname()[1])
        self.transport = fleet_mod.HttpTransport(self.addresses)
        for rid in REPLICAS:
            self._spawn(rid)
        for rid in REPLICAS:
            self._wait_healthy(rid)

    def _spawn(self, rid: str) -> None:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        host, port = self.addresses[rid]
        self._procs[rid] = self._subprocess.Popen(
            [sys.executable, "-m", "dist_svgd_tpu.serving.server",
             "--checkpoint", self._ckpt, "--model", "logreg",
             "--host", host, "--port", str(port),
             "--max-batch", str(self._max_batch), "--max-wait-ms", "1.0"],
            env=env, stdout=self._subprocess.DEVNULL,
            stderr=self._subprocess.DEVNULL,
        )

    def _wait_healthy(self, rid: str, timeout_s: float = 60.0) -> None:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            try:
                reply = self.transport.request(rid, "GET", "/healthz",
                                               timeout_s=1.0)
                if reply.status == 200:
                    return
            except fleet_mod.TransportError:
                pass
            time.sleep(0.2)
        raise RuntimeError(f"replica {rid} never became healthy")

    def kill(self, rid):
        self._procs[rid].kill()
        self._procs[rid].wait(timeout=10)

    def partition(self, rid):
        self.transport.partition(rid)

    def heal(self, rid):
        self.transport.heal(rid)

    def restart(self, rid):
        self._spawn(rid)
        self._wait_healthy(rid)

    def close(self):
        for p in self._procs.values():
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:
                pass

    def assert_partition_clean(self, rid) -> Dict[str, Any]:
        """Bypass the router-side cut: a direct probe (fresh transport, no
        deny-list) must still see a live, healthy process."""
        direct = fleet_mod.HttpTransport(self.addresses)
        try:
            reply = direct.request(rid, "GET", "/healthz", timeout_s=2.0)
            return {"alive": reply.status == 200, "flight_trips": 0,
                    "served_during_partition": None}
        except fleet_mod.TransportError:
            return {"alive": False, "flight_trips": None,
                    "served_during_partition": None}


# --------------------------------------------------------------------- #


def run_drill(mode: str = "fake", *, rate_hz: float = 200.0,
              steady_s: float = 0.6, kill_s: float = 0.8,
              partition_s: float = 0.8, probe_interval_s: float = 0.05,
              open_cooldown_s: float = 0.25,
              readmit_timeout_s: float = 10.0,
              hedge: bool = False) -> Dict[str, Any]:
    """Run the drill, return the ``fleet_failover`` row dict."""
    if mode not in ("fake", "real"):
        raise ValueError(f"mode must be fake|real, got {mode!r}")
    registry = MetricsRegistry()
    tmpdir = None
    if mode == "real":
        import tempfile

        tmpdir = tempfile.TemporaryDirectory(prefix="fleet_drill_")
        backend = _RealFleet(tmpdir.name)
        probe_interval_s = max(probe_interval_s, 0.1)
    else:
        backend = _FakeFleet()
    t_wall0 = time.monotonic()
    replica_set = fleet_mod.ReplicaSet(
        REPLICAS, backend.transport,
        probe_interval_s=probe_interval_s,
        probe_timeout_s=0.5 if mode == "real" else 0.2,
        fail_threshold=2, passive_fail_threshold=2,
        open_cooldown_s=open_cooldown_s,
        registry=registry,
    )
    router = fleet_mod.FleetRouter(
        list(REPLICAS), transport=backend.transport,
        replica_set=replica_set,
        max_retries=2, per_try_timeout_s=1.0 if mode == "real" else 0.5,
        default_deadline_s=5.0,
        backoff=Backoff(base_s=0.005, factor=2.0, max_s=0.05,
                        jitter_frac=0.2),
        hedge=hedge, registry=registry,
    )
    router.start()
    load = _OpenLoopLoad(router, rate_hz,
                         tenant_in_body=mode == "fake").start()
    partition_clean = None
    try:
        # phase 1: steady state
        load.phase[0] = "steady"
        time.sleep(steady_s)

        # phase 2: kill r0 under load — retries must absorb every request
        load.phase[0] = "kill"
        t_kill = time.monotonic()
        backend.kill("r0")
        time.sleep(kill_s)
        ts_open = _transition_ts(replica_set, "r0", "open", t_kill)
        detect_s = None if ts_open is None else ts_open - t_kill

        # phase 3: partition r1 (alive, unreachable) — same ejection path
        load.phase[0] = "partition"
        t_part = time.monotonic()
        backend.partition("r1")
        time.sleep(partition_s)
        partition_clean = backend.assert_partition_clean("r1")
        backend.heal("r1")

        # phase 4: restart r0 — must come back through half-open
        load.phase[0] = "restart"
        t_restart = time.monotonic()
        backend.restart("r0")
        deadline = time.monotonic() + readmit_timeout_s
        ts_closed = None
        while time.monotonic() < deadline:
            ts_closed = _transition_ts(replica_set, "r0", "closed", t_restart)
            if ts_closed is not None:
                break
            time.sleep(probe_interval_s / 2)
        readmit_s = None if ts_closed is None else ts_closed - t_restart
        load.phase[0] = "cooldown"
    finally:
        load.stop()
        router.shutdown()
        backend.close()
        if tmpdir is not None:
            tmpdir.cleanup()

    steady = load.counts("steady")
    kill = load.counts("kill")
    part = load.counts("partition")
    restart = load.counts("restart")
    total = steady["total"] + kill["total"] + part["total"] + restart["total"]
    lost = (steady["lost"] + kill["lost"] + part["lost"] + restart["lost"])
    shed = (steady["shed"] + kill["shed"] + part["shed"] + restart["shed"])
    availability = (1.0 if kill["total"] == 0
                    else kill["ok"] / max(kill["total"] - kill["shed"], 1))

    def _counter_sum(name: str) -> float:
        metric = registry._metrics.get(name)
        if metric is None:
            return 0
        with metric._lock:
            return sum(metric._series.values())

    row = {
        "metric": "fleet_failover",
        "value": round(availability, 6),
        "unit": "non-shed availability during single-replica loss",
        "mode": mode,
        "replicas": len(REPLICAS),
        "rate_hz": rate_hz,
        "requests": total,
        "lost_requests": lost,
        "shed_requests": shed,
        "detect_s": None if detect_s is None else round(detect_s, 4),
        "detect_probe_intervals": (
            None if detect_s is None
            else round(detect_s / probe_interval_s, 2)),
        "readmit_s": None if readmit_s is None else round(readmit_s, 4),
        "p99_steady_ms": steady["p99_ms"],
        "p99_kill_ms": kill["p99_ms"],
        "p99_partition_ms": part["p99_ms"],
        "retries": int(_counter_sum("svgd_fleet_retries_total")),
        "hedges": int(_counter_sum("svgd_fleet_hedges_total")),
        "failovers": int(_counter_sum("svgd_fleet_failovers_total")),
        "misroutes": int(_counter_sum("svgd_fleet_misroutes_total")),
        "ejections": int(_counter_sum("svgd_fleet_ejections_total")),
        "readmissions": int(_counter_sum("svgd_fleet_readmissions_total")),
        "partition_replica_alive": (
            None if partition_clean is None else partition_clean["alive"]),
        "partition_flight_trips": (
            None if partition_clean is None
            else partition_clean["flight_trips"]),
        "probe_interval_s": probe_interval_s,
        "open_cooldown_s": open_cooldown_s,
        "status_counts": {
            str(s): sum(1 for _, st, _ in load.records if st == s)
            for s in sorted({st for _, st, _ in load.records})},
        "wall_s": round(time.monotonic() - t_wall0, 3),
    }
    return row


def row_ok(row: Dict[str, Any]) -> Tuple[bool, List[str]]:
    """The unconditional correctness gates ``perf_regress`` applies to a
    ``fleet_failover`` row (speed is windowed separately)."""
    why = []
    if row["lost_requests"] > 0:
        why.append(f"lost {row['lost_requests']} non-shed request(s) — "
                   "retries failed to absorb a replica loss")
    if row["misroutes"] > 0:
        why.append(f"{row['misroutes']} request(s) routed to an ejected "
                   "replica")
    if row["detect_s"] is None:
        why.append("the killed replica was never ejected")
    if row["readmit_s"] is None:
        why.append("the restarted replica was never re-admitted")
    if row["readmissions"] < 1:
        why.append("no half-open readmission observed")
    if row["partition_replica_alive"] is False:
        why.append("the partitioned replica died — partition must leave "
                   "the process untouched")
    if row["partition_flight_trips"] not in (None, 0):
        why.append("partition tripped the replica's own flight recorder")
    return (not why), why


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("fake", "real"), default="fake")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="open-loop request rate (req/s)")
    ap.add_argument("--hedge", action="store_true",
                    help="enable tail hedging in the router under drill")
    args = ap.parse_args(argv)
    row = run_drill(mode=args.mode, rate_hz=args.rate, hedge=args.hedge)
    ok, why = row_ok(row)
    row["ok"] = ok
    if why:
        row["failures"] = why
    print(json.dumps(row), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
