"""Perf regression gate: re-measure the headline rows against recorded
incumbents (round-4 VERDICT item 8).

The measured wins in ``docs/notes.md`` (north-star φ, warm-started W2,
covertype bf16x3, the config-1 dispatch floor) previously lived only in
prose and ad-hoc tools; this gate re-measures them in ONE command and
red-flags a regression at a noise-aware threshold, institutionalising the
A/B timing protocol those notes derived:

- **chained fenced samples** — every timing is the mean wall of a chain of
  state-chained scan dispatches under one trailing scalar fetch
  (``bench._timed_chain``'s protocol: the ~0.1 s tunnel round trip is fixed
  per sample, so chains amortise it away and per-call eager timing is
  meaningless);
- **interleaved rounds** — one sample of *every* bench per round, rounds
  repeated; per-bench the min across rounds is kept.  A pool slowdown in
  one round hits all benches together instead of biasing whichever config
  was measured last (the incumbent-first / idle-credit artifacts measured
  in round 2, docs/notes.md timing-protocol notes);
- **noise-aware threshold** — the shared pool swings ±40% *between*
  sessions; min-of-interleaved-chains removes most of the within-session
  spread, so the default gate fails a row only when it lands >35% below its
  incumbent (``--tol``), and warns from half that;
- **windowed incumbents (round 8)** — each ``--record`` appends to a
  per-row *history window* (``_history`` in the incumbents file, newest
  ``--window`` runs) and the gate compares against the window's **median**,
  widening the band to ``mad_scale × MAD/median`` when the window itself is
  noisier than ``--tol`` says.  One lucky fast session can no longer ratchet
  the bar to a level the pool only hits 10% of the time, and a genuinely
  noisy row (relative MAD above the tol) self-documents its spread instead
  of flapping.  Legacy single-value incumbents seed a 1-point window.

- **serving telemetry rows (round 10)** — the serve round additionally
  gates ``serve_latency_p99`` (the telemetry histogram's tail latency over
  the timed window, judged lower-is-better with the same median+MAD
  windows — rps can hold while the tail fattens) and ``telemetry_overhead``
  (tracer-off/on A/B via ``serve_bench.measure_telemetry_overhead``;
  FAILs above a fixed 3% ceiling, never recorded as an incumbent).

- **mesh-sharded serving rows (round 12)** — ``serve_sharded`` (the same
  load shape as ``serve_throughput`` with the ensemble particle-sharded
  across every device and ``SERVE_SHARDED_LANES`` batcher lanes) and
  ``serve_sharded_p99`` gate against their own median+MAD incumbent
  windows; the zero-in-window-recompile FAIL applies to the sharded
  window unchanged, and the row reports ``vs_single_device`` (the ISSUE-7
  ≥4× acceptance ratio) alongside per-lane fairness counts.

- **multi-tenant registry rows (round 14)** — ``serve_multitenant``
  (``serve_bench.run_multitenant_bench``: 10 heterogeneous tenants —
  mixed logreg/BNN/GMM shapes — behind ONE ``ModelRegistry``, round-robin
  closed-loop load) gates its total rps and worst-tenant p99 against
  their own median+MAD windows, FAILs unconditionally on ANY cross-tenant
  steady-state recompile in the timed window (bucket misses or sentry
  compiles — tenants must not churn each other's kernels), and FAILs
  when either protective-machinery probe comes back empty (the LRU
  eviction probe must observe ≥ 1 eviction, the quota probe ≥ 1
  priority shed) — a bench that cannot exercise its own safety rails is
  broken, not lucky.  ``tenant_fairness`` is reported for the record.

- **elastic-capacity rows (round 13)** — ``elastic_resume``
  (``tools/elastic_drill.py``: device-loss → reshard-to-smaller-mesh →
  resume → serve) is gated on correctness unconditionally (resharded resume
  pinned to the uninterrupted run, grow-back and non-dividing fallback and
  serve-from-resharded-checkpoint all green, and ZERO steady-state
  recompiles after the one reshard compile — retrace-sentry enforced), and
  its ``elastic_reshard_wall_s`` / ``elastic_recovery_wall_s`` walls gate
  against their own median+MAD incumbent windows.

- **fleet-failover rows (round 15)** — ``fleet_failover``
  (``tools/fleet_drill.py`` in real-subprocess mode: 3 CPU replica
  processes behind the consistent-hash ``FleetRouter``, SIGKILL one under
  open-loop load, partition a second router-side, restart the first) is
  gated on correctness unconditionally — zero lost non-shed requests
  during single-replica loss, zero requests routed to an ejected replica,
  the kill detected and the restart re-admitted through half-open, and
  the partitioned replica process provably untouched — while
  ``fleet_detect_s`` / ``fleet_readmit_s`` gate against their own
  median+MAD incumbent windows.

- **fleet observability gates (round 16)** — ``fleet_trace_stitch``
  (a fake-mode ``fleet_drill`` run whose per-process trace exports are
  stitched by ``trace_report.stitch_files``) FAILs unconditionally when
  coverage drops below 1.0 — any served request whose router and replica
  spans no longer join on the ``X-Fleet-Trace`` id — or when a federated
  counter rollup ever decreases across scrapes (the counter-reset clamp
  broke).  ``fleet_federation_scrape_ms`` (the real-subprocess drill's
  median federation sweep wall) gates against its own median+MAD window.
  The existing 3% ``telemetry_overhead`` ceiling stays binding with trace
  propagation enabled: while tracing is on, every batcher submit mints
  and threads a trace id, so the tracer-on A/B arm prices propagation in.

- **traffic-at-scale gates (round 18)** — ``serve_storm``
  (``tools/workload_replay.py:run_storm``: the seeded multi-tenant
  steady → flash-crowd 2×-overload burst → recovery trace, replayed
  identically against static configurations and against the
  ``serving/autoscale.py`` controller).  Unconditional FAILs: any lost
  non-shed request in any arm (an admitted request must resolve), and
  any steady-state recompile inside the sentried replay windows.
  ``storm_goodput_2x`` (the adaptive arm's whole-storm POLITE goodput —
  the non-flooding tenants' completions within the latency objective per
  second) and ``storm_recover_s`` (burst end → first healthy polite
  second) gate
  against their own median+MAD windows; the adaptive-vs-best-static A/B
  (``ab.adaptive_wins``, goodput ratio, breach delta) is reported in
  the row for the record — the shared box's host-phase swings make a
  hard win-gate flappy, and the incumbent windows do the
  regression-catching.

- **sub-quadratic φ gates (round 17)** — ``large_n_approx``
  (``tools/large_n.py:run_approx_row``: the RFF feature-space φ at a
  particle count whose exact O(n²) step is off the dispatch budget
  entirely, extrapolated from a same-run exact probe) FAILs
  unconditionally when the small-n exact-vs-approx error pin breaches the
  declared budget (``ops/approx.py:default_error_budget`` — approximation
  drift is wrongness, not slowness) or when the timed window holds ANY
  steady-state recompile; its throughput gates against a median+MAD
  window like the other compute rows.

- **cross-host training gates (round 19)** — ``multihost_train``
  (``tools/multihost_train.py:run_drill``: W-process DCN-mesh training
  with host-sharded per-process checkpoints and a kill-one-worker elastic
  resume at W−1 on the same absolute step grid).  Unconditional FAILs:
  a non-bitwise multi-process-topology resume, a minibatch RNG root that
  changed across process layouts, steps lost differing from the
  checkpoint-grid expectation, a kill-one resume diverging past the
  drill tolerance, or ANY post-restart steady-state recompile.
  ``multihost_ring_hop_wall_ms`` (ring-exchange wall per hop) and
  ``multihost_updates_per_s`` (the gather arm) gate against their own
  median+MAD windows; an honest up-front refusal on a platform that
  cannot run the federation (``status='unsupported'`` naming the jax
  version) is reported UNSUPPORTED, not FAILed — the NO_MESH pattern.

- **streaming-freshness gates (round 20)** — ``freshness``
  (``tools/freshness_drill.py:run_drill``: a manual-clock bitwise
  kill→resume replay of the streaming pipeline, then a real-clock
  ingest → train → checkpoint → hot-reload loop with a calibrated
  label-flip ``DriftAt``).  Unconditional FAILs (``row_ok``): ANY
  dropped stream batch (data loss is loud by contract), a non-bitwise
  mid-stream resume, a drift breach served without a timely re-fit, any
  steady-state recompile beyond the documented per-reload kernel
  rebuilds, or a breached streaming SLO.  ``freshness_p99_s`` (p99
  event-time → first-serve latency) gates against its own median+MAD
  window.

- **progressive-delivery gates (round 21)** — ``canary_rollout``
  (``tools/rollout_drill.py:run_drill``: shadow-mirrored traffic, a
  staged 2 % → 10 % → 50 % → 100 % canary judged on generation-labelled
  SLO windows, automatic promotion, and a ``BadGenerationAt`` candidate
  the divergence window must kill).  Unconditional FAILs (``row_ok``):
  the good candidate not reaching promotion, ANY lost or errored client
  request across the phases, any steady-state recompile inside the
  sentried rollout windows, the bad candidate surviving or exceeding
  its configured exposure stage, any checkpoint read on the rollback
  path (rollback swaps to the resident incumbent in O(1)), a
  non-bitwise incumbent after rollback, or shadow-mirroring p99
  overhead at/over the drill bound.  ``rollout_promote_s`` (offer →
  full promotion wall) and ``shadow_overhead_frac`` (client p99
  inflation while mirroring, judged on a +1 offset — the healthy value
  is 0) gate against their own median+MAD windows.

- **program-card sibling gate (round 22)** — the *static* half of this
  gate lives in ``tools/program_audit.py``: per-plan program cards
  (collective inventory, donation aliasing, materialized-n×n, dtype
  promotions — lowered on the CPU box, no TPU and no timing noise)
  judged against ``tools/program_cards.json`` with the same
  ``--record`` / ``--list-missing`` conventions as this file.  A plan
  that grows a collective or drops donation fails *there*
  deterministically before it ever reaches these timed rows; this
  file's ``--list-missing`` cross-reports the sibling so one command
  audits both artifacts.

- **cost-attribution gates (round 23)** — ``cost_attribution``
  (``tools/cost_drill.py:run_drill``: one multi-tenant serve window with
  the dispatch profiler AND the usage meter enabled, under the retrace
  sentry, with a telemetry-history recorder snapshotting between window
  segments).  Unconditional FAILs (``row_ok``): attributed per-program
  dispatch wall under 95 % of the measured dispatch-wall window,
  per-tenant device-seconds not summing to the total within 1 % (an
  accounting identity, not a noise band), or ANY in-window recompile
  (kernel-cache misses, usage compile counts, or sentry compiles).  The
  profiler's own serve cost (``profiler_overhead``, interleaved
  off/on best-of A/B from the same drill) FAILs above the same fixed
  3 % ceiling as the tracer; ``cost_attr_rps`` (the measured window's
  closed-loop throughput) gates against its own median+MAD window.

- **retrace sentry (round 9)** — the timed rounds and the serving window
  both run under ``tools/jaxlint``'s ``retrace_sentry``: after the untimed
  warm-up pass, ANY XLA compilation inside a measurement window is a
  retrace bug (a shape that escaped the caches, a Python scalar baked into
  a jaxpr) and an unconditional FAIL regardless of throughput — the
  ``steady_state_recompiles`` row, plus ``sentry_compiles`` on the
  ``serve_throughput`` row.

Usage (on the TPU host)::

    python tools/perf_regress.py            # compare vs tools/perf_incumbents.json
    python tools/perf_regress.py --record   # overwrite incumbents with this run
    python tools/perf_regress.py --rounds 5 --tol 0.25

Prints one JSON line per row plus a summary line; exit code 1 if any row
FAILs.  Run it before adopting any perf-relevant change; after a *verified*
improvement, ``--record`` promotes the new numbers to incumbents.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import (
    N_PARTICLES,
    NUM_SHARDS,
    _fence,
    _make_phi_kernel_bench,
    _make_sharded,
    _TUNNEL_RT_S,
)

INCUMBENTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "perf_incumbents.json")

#: Per-row widening of ``--tol``.  The small-config rows measure the relay
#: as much as the chip: a config-1 "step" is ~2 µs of compute under ~0.2 ms
#: of per-dispatch marginal (docs/notes.md step-floor decomposition), so
#: relay-latency phases swing it far outside the compute rows' band
#: (observed same-session: config1 at 0.54× incumbent while the north-star
#: and W2 rows sat at 1.0×).  The wider band still catches a real floor
#: regression (a 2× slower dispatch path fails at any relay state).
TOL_FACTOR = {"config1_ups": 2.0, "covertype_bf16x3_ups": 1.5,
              # the serving rows measure host thread scheduling + the
              # batcher's wait window as much as the chip — wider band
              "serve_throughput": 2.0, "serve_latency_p99": 2.0,
              "serve_sharded": 2.0, "serve_sharded_p99": 2.0,
              "serve_multitenant": 2.0, "serve_multitenant_p99": 2.0,
              # the elastic walls are dominated by host checkpoint I/O and
              # one-off XLA compiles — as scheduling-noisy as the serve rows
              "elastic_reshard_wall_s": 2.0, "elastic_recovery_wall_s": 2.0,
              # the fleet walls measure probe scheduling + subprocess
              # restart (readmit includes a cold jax import) — host-noisy
              "fleet_detect_s": 2.0, "fleet_readmit_s": 2.0,
              # the federation sweep is N sequential HTTP scrapes + a
              # dump merge — host-scheduling-noisy like the other walls
              "fleet_federation_scrape_ms": 2.0,
              # the approx row is one big chained dispatch like the compute
              # rows, but includes the exact-probe leg — modest widening
              "large_n_approx": 1.5,
              # the storm rows measure open-loop scheduling + the
              # controller's real-time reactions — the most host-noisy
              # rows in the suite
              "storm_goodput_2x": 2.0, "storm_recover_s": 2.0,
              # the multihost walls include cross-process DCN hops and
              # host checkpoint I/O — as host-noisy as the fleet walls
              "multihost_ring_hop_wall_ms": 2.0,
              "multihost_updates_per_s": 2.0,
              # freshness is train wall + checkpoint I/O + reload compile
              # under a real clock — host-noisy like the other walls
              "freshness_p99_s": 2.0,
              # the rollout walls are real-clock stage holds + open-loop
              # replay scheduling; the overhead frac is a p99-vs-p99
              # ratio on a 2-core box — the host-noisiest kind of row
              "rollout_promote_s": 2.0, "shadow_overhead_frac": 2.0,
              # the cost-drill window is closed-loop serving like the
              # serve rows — host-scheduling-noisy
              "cost_attr_rps": 2.0}

#: Every row key judged against a median+MAD incumbent window — the
#: ``--list-missing`` contract: a key listed here with no history in the
#: incumbents file is a gate that silently cannot fire.  Keep in the order
#: the rows print.
WINDOWED_ROWS = (
    "north_star_ups", "w2_warm_ms_per_step", "covertype_bf16x3_ups",
    "w2_streaming_100k_ms_per_step", "config1_ups",
    "phi_kernel_pairs_per_sec",
    "serve_throughput", "serve_latency_p99",
    "serve_sharded", "serve_sharded_p99",
    "serve_multitenant", "serve_multitenant_p99",
    "elastic_reshard_wall_s", "elastic_recovery_wall_s",
    "large_n_approx",
    "storm_goodput_2x", "storm_recover_s",
    "fleet_detect_s", "fleet_readmit_s", "fleet_federation_scrape_ms",
    "multihost_ring_hop_wall_ms", "multihost_updates_per_s",
    "freshness_p99_s",
    "rollout_promote_s", "shadow_overhead_frac",
    "cost_attr_rps",
)

#: Windowed rows whose source drill ALSO carries unconditional ``row_ok``
#: correctness gates — those fire with or without incumbent history, so
#: ``--list-missing`` annotates them: an empty window means the row's
#: *regression* gate cannot fire, not that the drill cannot gate at all.
UNCONDITIONAL_ROW_KEYS = frozenset({
    "large_n_approx",
    "storm_goodput_2x", "storm_recover_s",
    "fleet_detect_s", "fleet_readmit_s", "fleet_federation_scrape_ms",
    "multihost_ring_hop_wall_ms", "multihost_updates_per_s",
    "freshness_p99_s",
    "rollout_promote_s", "shadow_overhead_frac",
    "cost_attr_rps",
})

#: Hard ceiling on the span tracer's measured serve-bench cost (round 10):
#: the interleaved tracer-off/on A/B (``serve_bench.
#: measure_telemetry_overhead``) FAILs above this fraction regardless of
#: incumbents — "observability that slows the service down" is a regression
#: by definition, not a noise band question.
TELEMETRY_OVERHEAD_MAX = 0.03

#: Same fixed-ceiling discipline for the dispatch profiler + usage meter
#: (round 23): the interleaved off/on A/B inside ``tools/cost_drill.py``
#: FAILs above this fraction of closed-loop rps — always-on attribution
#: must stay cheap enough to leave on.
PROFILER_OVERHEAD_MAX = 0.03

#: Same fixed-ceiling discipline for the posterior diagnostics (round 11):
#: the diagnostics-on/off A/B over one warmed supervised run
#: (``fault_drill.measure_diagnostics_overhead``) FAILs above this.
DIAGNOSTICS_OVERHEAD_MAX = 0.03

#: serve_throughput row config (tools/serve_bench.py defaults at a fixed,
#: recorded load): logreg d=55, 10k-particle ensemble, 16 closed-loop
#: clients, mixed 1/4/16-row requests.
SERVE_BENCH_KW = dict(model="logreg", n_particles=10_000, n_features=54,
                      clients=16, requests=1500, rows=(1, 4, 16),
                      max_batch=256, max_wait_ms=2.0)

#: serve_sharded row config (round 12): the SAME load shape as
#: ``serve_throughput`` (so the two rows are directly comparable — the
#: ISSUE-7 acceptance ratio is sharded/single at equal batch shape), with
#: the ensemble particle-sharded across every device on the host and the
#: batcher running multiple dispatch lanes over the shared engine.
SERVE_SHARDED_LANES = 4

#: large_n_approx row config (round 17): the sub-quadratic RFF φ at a
#: particle count whose exact O(n²) step (extrapolated from the same-run
#: exact probe) would blow the single-dispatch watchdog outright — the
#: regime ROADMAP item 2 exists for.  The row's correctness gates are
#: unconditional: the small-n error pin must land inside the declared
#: budget and the timed window must hold zero steady-state recompiles;
#: throughput gates against its own median+MAD window.
LARGE_N_APPROX_KW = dict(n=2_000_000, method="rff", num_features=4096,
                         steps=3, samples=2, exact_probe_n=131_072)

#: serve_multitenant row config (round 14): 10 heterogeneous tenants
#: (mixed logreg/BNN/GMM shapes) behind one registry, the same client /
#: request-size load shape as serve_throughput split round-robin across
#: tenants.  The LRU bound defaults to exactly the working set inside
#: run_multitenant_bench, so the eviction probe is deterministic.
MULTITENANT_KW = dict(tenants=10, clients=16, requests=1500,
                      rows=(1, 4, 16), max_batch=256, max_wait_ms=2.0)

#: Band widening factor: a row's effective shortfall tolerance is
#: ``max(tol, MAD_SCALE · MAD/median)`` over its incumbent window.  3×MAD ≈
#: 2σ for a normal spread — wide enough that in-band pool noise doesn't
#: FAIL, tight enough that a real 2× regression fails at any recorded
#: spread (the band is capped at 0.9 like the per-row tol).
MAD_SCALE = 3.0


# --------------------------------------------------------------------- #
# noise-aware judging (pure helpers — unit-tested on CPU in
# tests/test_perf_regress.py; everything below main() needs the TPU)


def _median(vals):
    import statistics

    return statistics.median(vals)


def _mad(vals, med=None):
    """Median absolute deviation — the robust spread estimate (a single
    outlier session moves it far less than a stddev)."""
    med = _median(vals) if med is None else med
    return _median([abs(v - med) for v in vals])


def incumbent_history(incumbents: dict, key: str):
    """The row's incumbent window: ``_history[key]`` when recorded, else a
    1-point window seeded from the legacy scalar entry (so pre-window
    incumbent files keep gating unchanged)."""
    hist = incumbents.get("_history", {}).get(key)
    if hist:
        return list(hist)
    legacy = incumbents.get(key)
    return [legacy] if isinstance(legacy, (int, float)) else []


def missing_rows(incumbents: dict, expected=WINDOWED_ROWS):
    """Windowed row keys with NO incumbent history (neither a ``_history``
    window nor a legacy scalar): their gates return NO_INCUMBENT every run,
    i.e. they silently cannot fire.  ``--list-missing`` prints these so a
    recording session knows what it still owes the file."""
    return [k for k in expected if not incumbent_history(incumbents, k)]


def judge_row(value, history, tol, higher_better, mad_scale=MAD_SCALE):
    """Noise-aware verdict of ``value`` against a window of prior rows.

    The incumbent is the window **median**; the shortfall band is ``tol``
    widened to ``mad_scale × MAD/median`` when the window's own relative
    spread exceeds it (both capped at 0.9).  Returns ``(status, info)`` with
    ``status`` in ``PASS``/``WARN``/``FAIL``/``NO_INCUMBENT`` and ``info``
    carrying the judged numbers for the printed row."""
    if not history:
        return "NO_INCUMBENT", {"incumbent": None}
    med = _median(history)
    if med <= 0:
        return "NO_INCUMBENT", {"incumbent": med}
    band = min(max(tol, mad_scale * _mad(history, med) / med), 0.9)
    # regression ratio, oriented so >1 means better than incumbent
    ratio = value / med if higher_better else med / value
    info = {
        "incumbent": med,
        "window": len(history),
        "window_rel_mad": round(_mad(history, med) / med, 4),
        "band": round(band, 3),
        "vs_incumbent": round(ratio, 3),
    }
    if ratio < 1 - band:
        return "FAIL", info
    if ratio < 1 - band / 2:
        return "WARN", info
    return "PASS", info


def record_result(incumbents: dict, key: str, value, window: int) -> None:
    """Append ``value`` to the row's history window (seeding it from a
    legacy scalar incumbent first) and refresh the scalar entry to the
    window median — old readers of the file keep working."""
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    hist = incumbents.setdefault("_history", {}).setdefault(key, [])
    legacy = incumbents.get(key)
    if not hist and isinstance(legacy, (int, float)):
        hist.append(legacy)
    hist.append(value)
    del hist[:-window]
    incumbents[key] = _median(hist)


def _build_benches():
    """Construct the headline-row runners.  Each entry:
    ``key -> (run, to_value, unit, higher_better)`` where ``run()`` advances
    real sampler state (chains cannot be elided) and ``to_value(wall_per_run)``
    converts one run's wall seconds to the metric."""
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import (
        logreg_likelihood,
        logreg_prior,
        make_logreg_logp,
    )
    from dist_svgd_tpu.utils.datasets import load_benchmark, load_covertype
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    fold = load_benchmark("banana", 42)
    benches = {}

    # 1. north-star sharded φ (the bench.py headline)
    ns = _make_sharded(fold)
    benches["north_star_ups"] = (
        lambda: ns.run_steps(500, 3e-3),
        lambda w: N_PARTICLES * 500 / w,
        "updates/sec", True,
    )

    # 2. warm-started Sinkhorn W2 (carried duals in the scan state)
    w2 = _make_sharded(fold, wasserstein=True)
    benches["w2_warm_ms_per_step"] = (
        lambda: w2.run_steps(100, 3e-3, h=10.0),
        lambda w: w / 100 * 1e3,
        "ms/step", False,
    )

    # 3. covertype bf16x3 (big-d minibatched, the fast tier's home ground)
    cx, ct = load_covertype(50_000)
    ct_d = 1 + cx.shape[1]
    cov = dt.DistSampler(
        NUM_SHARDS, logreg_likelihood, None,
        init_particles_per_shard(0, N_PARTICLES, ct_d, NUM_SHARDS),
        data=(jnp.asarray(cx), jnp.asarray(ct)),
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False, shard_data=True, batch_size=256,
        log_prior=logreg_prior, phi_impl="pallas_bf16",
    )
    benches["covertype_bf16x3_ups"] = (
        lambda: cov.run_steps(100, 1e-4),
        lambda w: N_PARTICLES * 100 / w,
        "updates/sec", True,
    )

    # 4. streaming W2 at 100k particles (the HBM-cliff config: lane-dense
    # streaming solve, warm duals, harsh 3e-3/h=10 point) — same builder
    # as the bench rows, so the gate and the incumbent share one config
    w2s = _make_sharded(fold, wasserstein=True, n=100_000)
    benches["w2_streaming_100k_ms_per_step"] = (
        lambda: w2s.run_steps(5, 3e-3, h=10.0),
        lambda w: w / 5 * 1e3,
        "ms/step", False,
    )

    # 5. config-1 floor (100-particle single sampler — dispatch-bound row)
    logp = make_logreg_logp(fold.x_train, fold.t_train.reshape(-1))
    c1 = dt.Sampler(1 + fold.x_train.shape[1], logp)
    c1_state = {"out": None}

    def c1_run():
        c1_state["out"] = c1.run(
            100, 100, 3e-3, seed=0, record=False,
            initial_particles=c1_state["out"],
        )[0]
        return c1_state["out"]

    benches["config1_ups"] = (
        c1_run, lambda w: 100 * 100 / w, "updates/sec", True,
    )

    # 6. bare φ kernel on the north-star shapes — the same-session roofline
    # that normalises the utilisation-fraction gate below (a ratio of two
    # interleaved same-session measurements: pool noise cancels)
    phi_run, phi_pairs = _make_phi_kernel_bench(1 + fold.x_train.shape[1])
    benches["phi_kernel_pairs_per_sec"] = (
        phi_run, lambda w: phi_pairs / w, "pairs/sec", True,
    )
    return benches


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rounds", type=int, default=3,
                    help="interleaved measurement rounds (min kept)")
    ap.add_argument("--tol", type=float, default=0.35,
                    help="FAIL when a row lands this fraction below its "
                         "incumbent (warn from tol/2)")
    ap.add_argument("--target-s", type=float, default=1.0,
                    help="device work per fenced sample (chain sizing)")
    ap.add_argument("--record", action="store_true",
                    help="append this run to the incumbent history windows "
                         "(refused when any row FAILs — see --force)")
    ap.add_argument("--window", type=int, default=8,
                    help="incumbent history window per row (median + MAD "
                         "band judged over the newest N recorded runs)")
    ap.add_argument("--force", action="store_true",
                    help="allow --record even when rows FAIL (deliberately "
                         "lowering the bar, e.g. after a hardware change)")
    ap.add_argument("--list-missing", action="store_true",
                    help="print the windowed rows with no incumbent "
                         "history and exit (works off-TPU: it only reads "
                         "the incumbents file)")
    args = ap.parse_args()

    if args.list_missing:
        # before the TPU gate on purpose: auditing the incumbents file
        # needs no accelerator
        with open(INCUMBENTS_PATH) as fh:
            incumbents = json.load(fh)
        missing = missing_rows(incumbents)
        # the static sibling gate's artifact is audited in the same breath
        # (round 22): both files are CPU-readable, and a builder with no
        # baseline card is exactly a windowed row with no history — a gate
        # that silently cannot fire
        from tools import program_audit

        print(json.dumps({
            "windowed_rows": len(WINDOWED_ROWS),
            "missing": missing,
            # every missing row's windowed gate is dormant; the annotated
            # ones still hard-FAIL on their drill's row_ok correctness
            # checks even with an empty history
            "gates": {k: ("windowed+unconditional"
                          if k in UNCONDITIONAL_ROW_KEYS else "windowed")
                      for k in missing},
            "program_audit_missing": program_audit.missing_builders(
                program_audit.load_baseline()),
        }))
        sys.exit(0)

    import jax

    platform = jax.devices()[0].platform
    if platform != "tpu":
        print(json.dumps({"error": "perf_regress needs the TPU (the "
                          "incumbents are v5e numbers)", "platform": platform}))
        sys.exit(2)

    with open(INCUMBENTS_PATH) as fh:
        incumbents = json.load(fh)

    benches = _build_benches()

    # warm up / compile (untimed), then size each bench's chain once so a
    # fenced sample does ~target_s of device work
    reps = {}
    for key, (run, _, _, _) in benches.items():
        _fence(run())
        t0 = time.perf_counter()
        _fence(run())
        est = time.perf_counter() - t0
        marginal = max(est - _TUNNEL_RT_S, 2e-3)
        reps[key] = max(2, min(512, round(args.target_s / marginal)))

    # interleaved rounds: one fenced chained sample of EVERY bench per round.
    # The rounds run under the retrace sentry (tools/jaxlint): everything was
    # compiled during the warm-up/sizing pass above, so ANY in-round compile
    # is a retrace bug contaminating the timing — an unconditional FAIL, the
    # same steady-state contract the serving row carries.
    from tools.jaxlint.sentry import retrace_sentry

    best = {key: float("inf") for key in benches}
    with retrace_sentry("perf_regress measurement rounds") as rounds_sentry:
        for _ in range(args.rounds):
            for key, (run, _, _, _) in benches.items():
                t0 = time.perf_counter()
                out = None
                for _ in range(reps[key]):
                    out = run()
                _fence(out)
                best[key] = min(best[key],
                                (time.perf_counter() - t0) / reps[key])

    failures = 0
    results = {}
    row = {"bench": "steady_state_recompiles",
           "value": rounds_sentry.compiles,
           "unit": "XLA compiles in the timed rounds",
           "supported": rounds_sentry.supported}
    if rounds_sentry.supported and rounds_sentry.compiles:
        row["status"] = "FAIL"
        failures += 1
    else:
        row["status"] = "PASS" if rounds_sentry.supported else "NO_SENTRY"
    print(json.dumps(row), flush=True)
    for key, (_, to_value, unit, higher) in benches.items():
        value = to_value(best[key])
        row = {"bench": key, "value": round(value, 2), "unit": unit,
               "reps": reps[key]}
        tol = min(args.tol * TOL_FACTOR.get(key, 1.0), 0.9)
        status, info = judge_row(value, incumbent_history(incumbents, key),
                                 tol, higher)
        row.update(info)
        row["status"] = status
        if status == "FAIL":
            failures += 1
        results[key] = value
        print(json.dumps(row), flush=True)

    # derived φ-utilisation gate (round-4 VERDICT item 6): the north-star
    # step's pair rate (ups × n — each update is one row of n kernel-pair
    # interactions) over the SAME-SESSION bare-φ-kernel rate, both from the
    # interleaved rounds above, so pool noise cancels in the ratio and a
    # move means a genuine utilisation change.  Gated at a FIXED 15%
    # relative regression vs the incumbent fraction — tighter than the
    # throughput rows' ±40% noise band, defensibly
    frac_key = "north_star_roofline_fraction"
    if "north_star_ups" in results and "phi_kernel_pairs_per_sec" in results:
        fraction = (results["north_star_ups"] * N_PARTICLES
                    / results["phi_kernel_pairs_per_sec"])
        inc_frac = incumbents.get(frac_key)
        row = {"bench": frac_key, "value": round(fraction, 4),
               "unit": "step pairs/s over same-session bare-φ pairs/s",
               "incumbent": inc_frac}
        if inc_frac:
            ratio = fraction / inc_frac
            row["vs_incumbent"] = round(ratio, 3)
            if ratio < 0.85:
                row["status"] = "FAIL"
                failures += 1
            else:
                row["status"] = "PASS"
        else:
            row["status"] = "NO_INCUMBENT"
        results[frac_key] = round(fraction, 4)
        print(json.dumps(row), flush=True)

    # serving-throughput row (tools/serve_bench.py): a wall-clock closed-loop
    # measurement of the host+device request path, not a chained dispatch —
    # so it runs its own protocol (one full load-gen run per round, best
    # kept) instead of riding the chain sizing above.  Steady-state traffic
    # must never recompile: any bucket-cache miss inside the timed window is
    # an unconditional FAIL regardless of throughput.
    import serve_bench

    serve_key = "serve_throughput"
    serve_best = None
    # compile counters are summed over EVERY round, not read off the
    # best-throughput one: an intermittent retrace in a discarded round is
    # still a broken steady-state contract (the unconditional-FAIL rule)
    serve_recompiles = 0
    serve_sentry_compiles = 0
    sentry_supported = True
    for _ in range(args.rounds):
        srow = serve_bench.run_bench(**SERVE_BENCH_KW)
        serve_recompiles += srow["recompiles"]
        sc = srow.get("sentry_compiles")
        if sc is None:
            sentry_supported = False
        else:
            serve_sentry_compiles += sc
        if serve_best is None or srow["value"] > serve_best["value"]:
            serve_best = srow
    row = {"bench": serve_key, "value": serve_best["value"],
           "unit": "requests/sec",
           "p50_ms": serve_best["p50_ms"], "p99_ms": serve_best["p99_ms"],
           "batch_occupancy_mean": serve_best["batch_occupancy_mean"],
           "recompiles": serve_recompiles,
           "sentry_compiles": (serve_sentry_compiles if sentry_supported
                               else None),
           "slo_status": serve_best.get("slo_status")}
    if serve_recompiles or serve_sentry_compiles:
        # bucket-cache misses OR any raw XLA compile the sentry saw in any
        # round's timed window: either way the steady-state contract broke
        row["status"] = "FAIL"
        failures += 1
    elif serve_best.get("slo_status") == "breach":
        # a breaching slo_status in the bench row (p99 over the declared
        # objective, shed/error budget blown) is a FAIL regardless of raw
        # throughput — the row can get faster while violating its SLO
        row["status"] = "FAIL"
        row["slo"] = serve_best.get("slo")
        failures += 1
    else:
        tol = min(args.tol * TOL_FACTOR.get(serve_key, 1.0), 0.9)
        status, info = judge_row(
            serve_best["value"], incumbent_history(incumbents, serve_key),
            tol, True,
        )
        row.update(info)
        row["status"] = status
        if status == "FAIL":
            failures += 1
    results[serve_key] = serve_best["value"]
    print(json.dumps(row), flush=True)

    # tail-latency gate (round 10): the telemetry histogram's p99 over the
    # best round's timed window, judged lower-is-better with the same
    # median+MAD window discipline as the throughput rows — a serving
    # change can hold rps while fattening the tail, and this row is the
    # one that catches it
    lat_key = "serve_latency_p99"
    lat_val = serve_best.get(lat_key)
    row = {"bench": lat_key, "value": lat_val, "unit": "ms"}
    if not lat_val:
        # a missing/zero p99 over a non-empty request window means the
        # telemetry histogram plumbing broke — FAIL loudly instead of
        # silently running without the tail-latency gate
        row["status"] = "FAIL"
        row["error"] = ("empty serve-latency histogram: serve_bench row "
                        "carried no telemetry percentiles")
        failures += 1
    else:
        tol = min(args.tol * TOL_FACTOR.get(lat_key, 1.0), 0.9)
        status, info = judge_row(
            lat_val, incumbent_history(incumbents, lat_key), tol, False,
        )
        row.update(info)
        row["status"] = status
        if status == "FAIL":
            failures += 1
        results[lat_key] = lat_val
    print(json.dumps(row), flush=True)

    # mesh-sharded serving rows (round 12): the same load shape as
    # serve_throughput but with the ensemble particle-sharded across every
    # device and multiple batcher lanes — its throughput and p99 gate
    # against their own incumbent windows, and the zero-in-window-
    # recompile contract applies unchanged (sharded bucket kernels are
    # still shape-bucketed; any in-window compile FAILs).  The ISSUE-7
    # acceptance ratio (sharded ≥ 4× single-device at equal batch shape)
    # is reported for the record, not gated — the incumbent windows do
    # the regression-catching.
    n_dev = len(jax.devices())
    sharded_key = "serve_sharded"
    sharded_best = None
    if n_dev < 2:
        # no mesh can materialise: the rounds would just re-measure
        # serve_throughput under another name — skip them entirely
        print(json.dumps({"bench": sharded_key, "status": "NO_MESH",
                          "devices": n_dev}), flush=True)
    sharded_recompiles = 0
    sharded_sentry_compiles = 0
    sharded_sentry_supported = True
    for _ in range(args.rounds if n_dev >= 2 else 0):
        srow = serve_bench.run_bench(devices=n_dev,
                                     lanes=SERVE_SHARDED_LANES,
                                     **SERVE_BENCH_KW)
        sharded_recompiles += srow["recompiles"]
        sc = srow.get("sentry_compiles")
        if sc is None:
            sharded_sentry_supported = False
        else:
            sharded_sentry_compiles += sc
        if sharded_best is None or srow["value"] > sharded_best["value"]:
            sharded_best = srow
    if sharded_best is not None:
        row = {"bench": sharded_key, "value": sharded_best["value"],
               "unit": "requests/sec",
               "devices": sharded_best["devices"],
               "lanes": sharded_best["lanes"],
               "p50_ms": sharded_best["p50_ms"],
               "p99_ms": sharded_best["p99_ms"],
               "lane_fairness": sharded_best["lane_fairness"]["requests"],
               "vs_single_device": (round(sharded_best["value"]
                                          / serve_best["value"], 3)
                                    if serve_best["value"] else None),
               "recompiles": sharded_recompiles,
               "sentry_compiles": (sharded_sentry_compiles
                                   if sharded_sentry_supported else None),
               "slo_status": sharded_best.get("slo_status")}
        if sharded_best["devices"] < 2:
            # the mesh fell back inside run_bench (defensive — should not
            # happen once n_dev >= 2): report, don't gate
            row["status"] = "NO_MESH"
        elif sharded_recompiles or sharded_sentry_compiles:
            row["status"] = "FAIL"
            failures += 1
        elif sharded_best.get("slo_status") == "breach":
            row["status"] = "FAIL"
            row["slo"] = sharded_best.get("slo")
            failures += 1
        else:
            tol = min(args.tol * TOL_FACTOR.get(sharded_key, 1.0), 0.9)
            status, info = judge_row(
                sharded_best["value"],
                incumbent_history(incumbents, sharded_key), tol, True,
            )
            row.update(info)
            row["status"] = status
            if status == "FAIL":
                failures += 1
        if sharded_best["devices"] >= 2:
            results[sharded_key] = sharded_best["value"]
        print(json.dumps(row), flush=True)

    if sharded_best is not None and sharded_best["devices"] >= 2:
        sharded_lat_key = "serve_sharded_p99"
        sharded_lat = sharded_best.get("serve_latency_p99")
        row = {"bench": sharded_lat_key, "value": sharded_lat, "unit": "ms"}
        if not sharded_lat:
            row["status"] = "FAIL"
            row["error"] = ("empty sharded serve-latency histogram: "
                            "serve_sharded row carried no telemetry "
                            "percentiles")
            failures += 1
        else:
            tol = min(args.tol * TOL_FACTOR.get(sharded_lat_key, 1.0), 0.9)
            status, info = judge_row(
                sharded_lat, incumbent_history(incumbents, sharded_lat_key),
                tol, False,
            )
            row.update(info)
            row["status"] = status
            if status == "FAIL":
                failures += 1
            results[sharded_lat_key] = sharded_lat
        print(json.dumps(row), flush=True)

    # multi-tenant registry rows (round 14): 10 heterogeneous tenants
    # behind one ModelRegistry — cross-tenant recompile churn is an
    # unconditional FAIL (summed over every round, like the serve rows),
    # and so is a protective-machinery probe that observed nothing (the
    # LRU eviction and quota-priority-shed drills are deterministic by
    # construction; zero means the rail itself broke)
    mt_key = "serve_multitenant"
    mt_best = None
    mt_recompiles = 0
    mt_sentry_compiles = 0
    mt_sentry_supported = True
    for _ in range(args.rounds):
        mrow = serve_bench.run_multitenant_bench(**MULTITENANT_KW)
        mt_recompiles += mrow["recompiles"]
        sc = mrow.get("sentry_compiles")
        if sc is None:
            mt_sentry_supported = False
        else:
            mt_sentry_compiles += sc
        if mt_best is None or mrow["value"] > mt_best["value"]:
            mt_best = mrow
    row = {"bench": mt_key, "value": mt_best["value"],
           "unit": "requests/sec",
           "tenants": mt_best["tenants"],
           "tenant_fairness": mt_best["tenant_fairness"],
           "p99_worst_tenant_ms": mt_best["p99_worst_tenant_ms"],
           "evictions": mt_best["evictions"],
           "quota_sheds": mt_best["quota_sheds"],
           "recompiles": mt_recompiles,
           "sentry_compiles": (mt_sentry_compiles if mt_sentry_supported
                               else None)}
    if mt_recompiles or mt_sentry_compiles:
        # cross-tenant steady-state recompile churn in ANY round's timed
        # window: the multi-tenant contract broke regardless of throughput
        row["status"] = "FAIL"
        failures += 1
    elif mt_best["evictions"] < 1 or mt_best["quota_sheds"] < 1:
        row["status"] = "FAIL"
        row["error"] = ("protective machinery unobserved: eviction probe "
                        f"saw {mt_best['evictions']} evictions, quota "
                        f"probe {mt_best['quota_sheds']} priority sheds")
        failures += 1
    else:
        tol = min(args.tol * TOL_FACTOR.get(mt_key, 1.0), 0.9)
        status, info = judge_row(
            mt_best["value"], incumbent_history(incumbents, mt_key),
            tol, True,
        )
        row.update(info)
        row["status"] = status
        if status == "FAIL":
            failures += 1
        results[mt_key] = mt_best["value"]
    print(json.dumps(row), flush=True)

    mt_lat_key = "serve_multitenant_p99"
    mt_lat = mt_best.get("p99_worst_tenant_ms")
    row = {"bench": mt_lat_key, "value": mt_lat,
           "unit": "ms (worst tenant)"}
    if not mt_lat:
        row["status"] = "FAIL"
        row["error"] = ("empty multi-tenant latency distribution: the "
                        "serve_multitenant row carried no per-tenant p99")
        failures += 1
    else:
        tol = min(args.tol * TOL_FACTOR.get(mt_lat_key, 1.0), 0.9)
        status, info = judge_row(
            mt_lat, incumbent_history(incumbents, mt_lat_key), tol, False,
        )
        row.update(info)
        row["status"] = status
        if status == "FAIL":
            failures += 1
        results[mt_lat_key] = mt_lat
    print(json.dumps(row), flush=True)

    # telemetry-overhead gate (round 10): tracer-off vs tracer-on A/B on
    # the serve bench (interleaved rounds, best-of each arm) — a fixed
    # ceiling, not an incumbent window (and never recorded as one)
    ov = serve_bench.measure_telemetry_overhead(
        rounds=args.rounds, **SERVE_BENCH_KW)
    row = {"bench": "telemetry_overhead", "value": ov["overhead_frac"],
           "unit": "fraction of serve rps lost with tracing enabled",
           "rps_disabled": ov["rps_disabled"],
           "rps_enabled": ov["rps_enabled"],
           "ceiling": TELEMETRY_OVERHEAD_MAX}
    if ov["overhead_frac"] > TELEMETRY_OVERHEAD_MAX:
        row["status"] = "FAIL"
        failures += 1
    else:
        row["status"] = "PASS"
    print(json.dumps(row), flush=True)

    # diagnostics-overhead gate (round 11): posterior health checks must
    # stay within the same fixed 3% ceiling on the supervised training
    # loop — measured like the telemetry A/B, never recorded as an
    # incumbent
    import fault_drill

    dov = fault_drill.measure_diagnostics_overhead(rounds=args.rounds)
    row = {"bench": "diagnostics_overhead", "value": dov["overhead_frac"],
           "unit": "fraction of supervised-run wall added by diagnostics",
           "wall_off_s": dov["wall_off_s"], "wall_on_s": dov["wall_on_s"],
           "ceiling": DIAGNOSTICS_OVERHEAD_MAX}
    if dov["overhead_frac"] > DIAGNOSTICS_OVERHEAD_MAX:
        row["status"] = "FAIL"
        failures += 1
    else:
        row["status"] = "PASS"
    print(json.dumps(row), flush=True)

    # elastic-capacity gates (round 13): the elastic_resume drill — kill at
    # step k, reshard the checkpoint to a smaller mesh, resume, serve.  Two
    # unconditional correctness gates (any steady-state recompile AFTER the
    # one reshard compile is a retrace bug; a drill whose resharded resume
    # diverges, fails to grow back, crashes on a non-dividing target, or
    # cannot serve the resharded checkpoint is broken regardless of speed)
    # plus two windowed wall gates: reshard wall (restore+reshard+rebuild)
    # and recovery wall (reshard+backoff+replay to the detection step).
    import elastic_drill

    erow = elastic_drill.run_drill()
    correct = elastic_drill.drill_ok(erow)
    row = {"bench": "elastic_resume",
           "shards": f"{erow['shards_from']}->{erow['shards_to']}",
           "steps_lost": erow["steps_lost"],
           "post_reshard_recompiles": erow["post_reshard_recompiles"],
           "sentry_supported": erow["sentry_supported"],
           "elastic_final_max_dev": erow["elastic_final_max_dev"],
           "ksd_delta_frac": erow["ksd_delta_frac"],
           "grow_ok": erow["grow_ok"], "fallback_ok": erow["fallback_ok"],
           "serve_ok": erow["serve_ok"]}
    if not correct:
        row["status"] = "FAIL"
        failures += 1
    else:
        row["status"] = "PASS"
    print(json.dumps(row), flush=True)
    if correct:
        for key, field in (("elastic_reshard_wall_s", "reshard_wall_s"),
                           ("elastic_recovery_wall_s", "recovery_wall_s")):
            value = erow[field]
            row = {"bench": key, "value": value, "unit": "s"}
            if value is None:
                row["status"] = "FAIL"
                row["error"] = f"drill row carried no {field}"
                failures += 1
            else:
                tol = min(args.tol * TOL_FACTOR.get(key, 1.0), 0.9)
                status, info = judge_row(
                    value, incumbent_history(incumbents, key), tol, False,
                )
                row.update(info)
                row["status"] = status
                if status == "FAIL":
                    failures += 1
                results[key] = value
            print(json.dumps(row), flush=True)

    # sub-quadratic φ gates (round 17): the large_n_approx row — RFF φ at
    # a particle count whose exact step (extrapolated quadratically from
    # the same-run exact probe) is off the dispatch budget entirely.  Two
    # unconditional correctness gates (the small-n error pin must land
    # inside the declared budget — an approximation drifting out of its
    # budget is wrongness, not slowness; and zero steady-state recompiles
    # in the timed window) plus a median+MAD throughput window.
    import large_n as large_n_mod

    arow = large_n_mod.run_approx_row(**LARGE_N_APPROX_KW)
    a_ok, a_why = large_n_mod.approx_row_ok(arow)
    ln_key = "large_n_approx"
    row = {"bench": ln_key, "value": arow["updates_per_sec"],
           "unit": "updates/sec", "n": arow["n"], "method": arow["method"],
           "dial": arow["dial"],
           "approx_rel_err": arow["approx_rel_err"],
           "error_budget": arow["error_budget"],
           "within_budget": arow["within_budget"],
           "recompiles": arow["recompiles"],
           "exact_est_wall_per_step_s": arow["exact_est_wall_per_step_s"],
           "est_speedup_vs_exact": arow["est_speedup_vs_exact"]}
    if not a_ok:
        row["status"] = "FAIL"
        row["error"] = "; ".join(a_why)
        failures += 1
    else:
        tol = min(args.tol * TOL_FACTOR.get(ln_key, 1.0), 0.9)
        status, info = judge_row(
            arow["updates_per_sec"], incumbent_history(incumbents, ln_key),
            tol, True,
        )
        row.update(info)
        row["status"] = status
        if status == "FAIL":
            failures += 1
        results[ln_key] = arow["updates_per_sec"]
    print(json.dumps(row), flush=True)

    # traffic-at-scale gates (round 18): the serve_storm row — the seeded
    # flash-crowd overload trace replayed against static configs and the
    # autoscale controller.  Unconditional FAILs on any lost non-shed
    # request or any in-window steady-state recompile (workload_replay.
    # storm_ok); the adaptive goodput and recovery wall gate against
    # their own median+MAD windows; the A/B verdict rides the row.
    import workload_replay

    wrow = workload_replay.run_storm()
    w_ok, w_why = workload_replay.storm_ok(wrow)
    storm_key = "storm_goodput_2x"
    row = {"bench": "serve_storm", "value": wrow[storm_key],
           "unit": wrow["unit"],
           "capacity_rows_per_s": wrow["capacity_rows_per_s"],
           "p99_breach_s": wrow["storm_p99_breach_s"],
           "recover_s": wrow["storm_recover_s"],
           "lost_requests": wrow["lost_requests"],
           "shed_requests": wrow["shed_requests"],
           "recompiles": wrow["recompiles"],
           "sentry_compiles": wrow["sentry_compiles"],
           "ab": wrow["ab"]}
    if not w_ok:
        row["status"] = "FAIL"
        row["error"] = "; ".join(w_why)
        failures += 1
    else:
        tol = min(args.tol * TOL_FACTOR.get(storm_key, 1.0), 0.9)
        status, info = judge_row(
            wrow[storm_key], incumbent_history(incumbents, storm_key),
            tol, True,
        )
        row.update(info)
        row["status"] = status
        if status == "FAIL":
            failures += 1
        results[storm_key] = wrow[storm_key]
    print(json.dumps(row), flush=True)
    if w_ok:
        rec_key = "storm_recover_s"
        rec_val = wrow["storm_recover_s"]
        row = {"bench": rec_key, "value": rec_val, "unit": "s"}
        # judged on a +1 s offset: an instant recovery is 0.0, and a
        # ratio against a zero median is undefined — the offset keeps the
        # lower-is-better window meaningful at the metric's 1 s
        # granularity
        hist = [h + 1.0 for h in incumbent_history(incumbents, rec_key)]
        tol = min(args.tol * TOL_FACTOR.get(rec_key, 1.0), 0.9)
        status, info = judge_row(rec_val + 1.0, hist, tol, False)
        row.update(info)
        row["status"] = status
        if status == "FAIL":
            failures += 1
        results[rec_key] = rec_val
        print(json.dumps(row), flush=True)

    # fleet-failover gates (round 15): the real-subprocess drill — 3 CPU
    # replica processes behind the router, SIGKILL one under open-loop
    # load, partition another, restart the first.  Correctness gates are
    # unconditional (fleet_drill.row_ok): ANY lost non-shed request during
    # single-replica loss, ANY request routed to an ejected replica, a
    # never-ejected kill, a never-readmitted restart, or a partition that
    # touched the replica process — all FAIL regardless of speed.  The
    # detection and readmit walls gate against their own median+MAD
    # incumbent windows (readmit includes the replica's cold start by
    # design — that IS the recovery the fleet user waits for).
    import fleet_drill

    frow = fleet_drill.run_drill(mode="real")
    fleet_ok, fleet_why = fleet_drill.row_ok(frow)
    row = {"bench": "fleet_failover", "value": frow["value"],
           "unit": frow["unit"], "mode": frow["mode"],
           "replicas": frow["replicas"], "requests": frow["requests"],
           "lost_requests": frow["lost_requests"],
           "shed_requests": frow["shed_requests"],
           "misroutes": frow["misroutes"],
           "detect_probe_intervals": frow["detect_probe_intervals"],
           "p99_partition_ms": frow["p99_partition_ms"],
           "partition_replica_alive": frow["partition_replica_alive"],
           "partition_flight_trips": frow["partition_flight_trips"]}
    if not fleet_ok:
        row["status"] = "FAIL"
        row["error"] = "; ".join(fleet_why)
        failures += 1
    else:
        row["status"] = "PASS"
    print(json.dumps(row), flush=True)
    if fleet_ok:
        for key, field in (("fleet_detect_s", "detect_s"),
                           ("fleet_readmit_s", "readmit_s"),
                           ("fleet_federation_scrape_ms",
                            "federation_scrape_ms")):
            value = frow[field]
            row = {"bench": key, "value": value,
                   "unit": "ms" if key.endswith("_ms") else "s"}
            tol = min(args.tol * TOL_FACTOR.get(key, 1.0), 0.9)
            status, info = judge_row(
                value, incumbent_history(incumbents, key), tol, False,
            )
            row.update(info)
            row["status"] = status
            if status == "FAIL":
                failures += 1
            results[key] = value
            print(json.dumps(row), flush=True)

    # fleet observability gates (round 16): trace-stitch coverage from
    # the FAKE drill — its replica stand-ins model replicas streaming
    # their trace exports off-process, so EVERY served route must
    # reassemble into one router→replica tree on its X-Fleet-Trace id
    # (real mode cannot carry this gate: a SIGKILLed replica takes its
    # in-memory trace buffer with it).  Coverage below 1.0 — or a
    # non-monotone federated counter rollup (the restart clamp broke) —
    # is an unconditional FAIL regardless of every wall above.
    fake_frow = fleet_drill.run_drill(mode="fake")
    row = {"bench": "fleet_trace_stitch",
           "value": fake_frow.get("trace_stitch_coverage"),
           "unit": "fraction of served routes stitched to a replica tree",
           "served_routes": fake_frow.get("stitch_served_routes"),
           "retry_trees": fake_frow.get("stitch_retry_trees"),
           "orphans": fake_frow.get("stitch_orphans"),
           "federation_monotone": fake_frow.get("federation_monotone")}
    cov = fake_frow.get("trace_stitch_coverage")
    if cov is None or cov < 1.0:
        row["status"] = "FAIL"
        row["error"] = (f"stitch coverage {cov} < 1.0 — a served "
                        "request's router and replica spans no longer "
                        "join on the trace id")
        failures += 1
    elif fake_frow.get("federation_monotone") is False:
        row["status"] = "FAIL"
        row["error"] = ("a federated counter rollup decreased across "
                        "scrapes — the restart clamp broke")
        failures += 1
    else:
        row["status"] = "PASS"
    print(json.dumps(row), flush=True)

    # cross-host training gates (round 19): the multihost_train drill —
    # W-process mesh, host-sharded per-process checkpoints, SIGKILL one
    # worker, resume at W−1 on the same step grid.  Unconditional FAILs
    # (multihost_train.row_ok): non-bitwise multi-process-topology resume,
    # RNG root changed across layouts, steps lost off the checkpoint-grid
    # expectation, divergent kill-one resume, or any post-restart
    # steady-state recompile.  The ring-hop wall and gather-arm updates/s
    # gate against their own median+MAD windows.  A platform that cannot
    # run the federation refuses up front (status='unsupported' naming
    # the jax version) — reported UNSUPPORTED like NO_MESH, not FAILed.
    import multihost_train

    mh_row = multihost_train.run_drill(mode="auto")
    mh_ok, mh_why = multihost_train.row_ok(mh_row)
    row = {"bench": "multihost_train", "mode": mh_row.get("mode"),
           "status_detail": mh_row.get("status")}
    if mh_row.get("status") == "unsupported":
        row["status"] = "UNSUPPORTED"
        row["reason"] = mh_row.get("unsupported_reason")
        print(json.dumps(row), flush=True)
    else:
        row.update({
            "processes": mh_row.get("processes"),
            "shards": (f"{mh_row.get('shards')}->"
                       f"{mh_row.get('shards_after_loss')}"),
            "dcn_crossings_per_hop": mh_row.get("dcn_crossings_per_hop"),
            "resume_bitwise": mh_row.get("resume_bitwise"),
            "rng_layout_free": mh_row.get("rng_layout_free"),
            "steps_lost": mh_row.get("steps_lost"),
            "killone_max_dev": mh_row.get("killone_max_dev"),
            "post_restart_recompiles": mh_row.get(
                "post_restart_recompiles"),
            "federation_restarts": mh_row.get("federation_restarts"),
        })
        if not mh_ok:
            row["status"] = "FAIL"
            row["error"] = "; ".join(mh_why)
            failures += 1
        else:
            row["status"] = "PASS"
        print(json.dumps(row), flush=True)
        if mh_ok:
            for key, field, higher in (
                    ("multihost_ring_hop_wall_ms", "ring_hop_wall_ms",
                     False),
                    ("multihost_updates_per_s", "updates_per_s_gather",
                     True)):
                value = mh_row.get(field)
                row = {"bench": key, "value": value,
                       "unit": "ms" if key.endswith("_ms")
                       else "updates/sec"}
                if value is None:
                    row["status"] = "FAIL"
                    row["error"] = f"drill row carried no {field}"
                    failures += 1
                else:
                    tol = min(args.tol * TOL_FACTOR.get(key, 1.0), 0.9)
                    status, info = judge_row(
                        value, incumbent_history(incumbents, key), tol,
                        higher,
                    )
                    row.update(info)
                    row["status"] = status
                    if status == "FAIL":
                        failures += 1
                    results[key] = value
                print(json.dumps(row), flush=True)

    # streaming-freshness gates (round 20): the freshness drill — manual-
    # clock bitwise kill→resume replay, then a real-clock ingest → train →
    # checkpoint → hot-reload loop with a calibrated label-flip DriftAt.
    # Unconditional FAILs (freshness_drill.row_ok): any dropped stream
    # batch, a non-bitwise mid-stream resume, drift served without a
    # timely re-fit, any steady-state recompile beyond the documented
    # per-reload kernel rebuilds, or a breached streaming SLO.  The p99
    # event-time → first-serve latency gates against its own window.
    import freshness_drill

    fr_row = freshness_drill.run_drill()
    fr_ok, fr_why = freshness_drill.row_ok(fr_row)
    row = {"bench": "freshness",
           "freshness_p50_s": fr_row.get("freshness_p50_s"),
           "freshness_p99_s": fr_row.get("freshness_p99_s"),
           "resumed_bitwise_identical": fr_row.get(
               "resumed_bitwise_identical"),
           "drift_detect_segments": fr_row.get("drift_detect_segments"),
           "refits": fr_row.get("refits"),
           "reloads": fr_row.get("reloads"),
           "reload_rejections": fr_row.get("reload_rejections"),
           "dropped_total": fr_row.get("dropped_total"),
           "steady_state_recompiles": fr_row.get("steady_state_recompiles"),
           "slo_status": fr_row.get("slo_status")}
    if not fr_ok:
        row["status"] = "FAIL"
        row["error"] = "; ".join(fr_why)
        failures += 1
    else:
        row["status"] = "PASS"
    print(json.dumps(row), flush=True)
    if fr_ok:
        fr_key = "freshness_p99_s"
        fr_val = fr_row.get(fr_key)
        row = {"bench": fr_key, "value": fr_val, "unit": "s"}
        tol = min(args.tol * TOL_FACTOR.get(fr_key, 1.0), 0.9)
        status, info = judge_row(
            fr_val, incumbent_history(incumbents, fr_key), tol, False)
        row.update(info)
        row["status"] = status
        if status == "FAIL":
            failures += 1
        results[fr_key] = fr_val
        print(json.dumps(row), flush=True)

    # progressive-delivery gates (round 21): the rollout drill — shadow
    # mirroring off the client's critical path, a staged canary judged
    # on generation-labelled SLO windows, automatic promotion, and a
    # BadGenerationAt candidate the divergence window must roll back to
    # the still-resident incumbent without touching a checkpoint.
    import rollout_drill

    ro_row = rollout_drill.run_drill()
    ro_ok, ro_why = rollout_drill.row_ok(ro_row)
    ro_good = ro_row.get("good") or {}
    ro_bad = ro_row.get("bad") or {}
    row = {"bench": "canary_rollout",
           "rollout_promote_s": ro_row.get("rollout_promote_s"),
           "shadow_overhead_frac": ro_row.get("shadow_overhead_frac"),
           "good_stages": ro_good.get("stages"),
           "bad_at_stage": ro_bad.get("at_stage"),
           "bad_peak_fraction": ro_bad.get("peak_fraction"),
           "checkpoint_reloads_on_rollback": ro_bad.get(
               "checkpoint_reloads"),
           "client": ro_row.get("client"),
           "mirrors_total": ro_row.get("mirrors_total"),
           "mirror_dropped": ro_row.get("mirror_dropped"),
           "steady_state_recompiles": ro_row.get("steady_state_recompiles")}
    if not ro_ok:
        row["status"] = "FAIL"
        row["error"] = "; ".join(ro_why)
        failures += 1
    else:
        row["status"] = "PASS"
    print(json.dumps(row), flush=True)
    if ro_ok:
        ro_key = "rollout_promote_s"
        ro_val = ro_row.get(ro_key)
        row = {"bench": ro_key, "value": ro_val, "unit": "s"}
        tol = min(args.tol * TOL_FACTOR.get(ro_key, 1.0), 0.9)
        status, info = judge_row(
            ro_val, incumbent_history(incumbents, ro_key), tol, False)
        row.update(info)
        row["status"] = status
        if status == "FAIL":
            failures += 1
        results[ro_key] = ro_val
        print(json.dumps(row), flush=True)

        ov_key = "shadow_overhead_frac"
        ov_val = ro_row.get(ov_key)
        row = {"bench": ov_key, "value": ov_val, "unit": "frac"}
        tol = min(args.tol * TOL_FACTOR.get(ov_key, 1.0), 0.9)
        # judged on a +1 offset: the healthy overhead is 0.0, and a
        # ratio against a zero median is undefined — the offset keeps
        # the band meaningful near zero (the recover_s discipline)
        hist = [h + 1.0 for h in incumbent_history(incumbents, ov_key)]
        status, info = judge_row(ov_val + 1.0, hist, tol, False)
        row.update(info)
        row["status"] = status
        if status == "FAIL":
            failures += 1
        results[ov_key] = ov_val
        print(json.dumps(row), flush=True)

    # cost-attribution gates (round 23): the cost drill — one
    # multi-tenant serve window with the dispatch profiler + usage meter
    # enabled under the retrace sentry.  Unconditional FAILs
    # (cost_drill.row_ok): attributed dispatch wall under 95% of the
    # measured window, per-tenant device-seconds off the total by more
    # than 1% (an accounting identity), or any in-window recompile.
    import cost_drill

    ca_row = cost_drill.run_drill()
    ca_ok, ca_why = cost_drill.row_ok(ca_row)
    row = {"bench": "cost_attribution",
           "coverage": ca_row.get("coverage"),
           "attributed_s": ca_row.get("attributed_s"),
           "measured_device_s": ca_row.get("measured_device_s"),
           "tenant_device_s": ca_row.get("tenant_device_s"),
           "tenant_sum_err_frac": ca_row.get("tenant_sum_err_frac"),
           "recompiles": ca_row.get("recompiles"),
           "sentry_compiles": ca_row.get("sentry_compiles"),
           "history_records": ca_row.get("history_records"),
           "history_anomalies": ca_row.get("history_anomalies")}
    if not ca_ok:
        row["status"] = "FAIL"
        row["error"] = "; ".join(ca_why)
        failures += 1
    else:
        row["status"] = "PASS"
    print(json.dumps(row), flush=True)

    # profiler-overhead gate: the drill's interleaved off/on A/B against
    # the same fixed ceiling as the tracer — never recorded as an
    # incumbent ("attribution that slows serving down" is a regression
    # by definition)
    ca_ov = ca_row.get("profiler_overhead_frac")
    row = {"bench": "profiler_overhead", "value": ca_ov,
           "unit": "fraction of serve rps lost with profiler+metering on",
           "rps_disabled": ca_row.get("rps_disabled"),
           "rps_enabled": ca_row.get("rps_enabled"),
           "ceiling": PROFILER_OVERHEAD_MAX}
    if ca_ov is None or ca_ov > PROFILER_OVERHEAD_MAX:
        row["status"] = "FAIL"
        failures += 1
    else:
        row["status"] = "PASS"
    print(json.dumps(row), flush=True)

    if ca_ok:
        ca_key = "cost_attr_rps"
        ca_val = ca_row.get("rps")
        row = {"bench": ca_key, "value": ca_val, "unit": "req/s"}
        tol = min(args.tol * TOL_FACTOR.get(ca_key, 1.0), 0.9)
        status, info = judge_row(
            ca_val, incumbent_history(incumbents, ca_key), tol, True)
        row.update(info)
        row["status"] = status
        if status == "FAIL":
            failures += 1
        results[ca_key] = ca_val
        print(json.dumps(row), flush=True)

    print(json.dumps({
        "summary": "FAIL" if failures else "PASS",
        "failures": failures,
        "rounds": args.rounds,
        "tol": args.tol,
    }))
    if args.record and failures and not args.force:
        # never silently ratchet the bar down: recording a FAILing run would
        # launder the regression into the baseline every future gate passes
        print(json.dumps({
            "record_refused": f"{failures} row(s) FAILed; pass --force to "
                              "deliberately lower the incumbents"
        }))
        sys.exit(1)
    if args.record:
        # append to each row's history window; the scalar entry becomes the
        # window median (legacy readers of the file keep working).  The
        # roofline fraction keeps its fixed-threshold scalar (it is already
        # a same-session ratio — pool noise cancels in it by construction).
        for key, value in results.items():
            if key == "north_star_roofline_fraction":
                incumbents[key] = value
            else:
                record_result(incumbents, key, value, args.window)
        incumbents["recorded"] = (
            f"perf_regress --record (rounds={args.rounds}, "
            f"window={args.window}) on {platform}"
        )
        with open(INCUMBENTS_PATH, "w") as fh:
            json.dump(incumbents, fh, indent=2)
            fh.write("\n")
        print(json.dumps({"recorded_to": INCUMBENTS_PATH}))
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
