"""Measure the history-recording overhead of the logreg driver's chunked
``record=True`` path at large n (round-5, VERDICT r04 item 5: a recorded
100k-particle run must complete with history overhead <10% of step time).

Times, interleaved (one sample of each per round, min kept — the repo's
A/B protocol):

- **plain**: the same trajectory as chunk-sized ``run_steps`` dispatches
  with ``record=False`` (the pure step cost at the driver's dispatch
  granularity);
- **recorded**: the driver's actual loop (``experiments/logreg.py``) —
  HBM-budget-sized chunks (``record_chunk_steps``), the device history
  stack D2H-copied while the next chunk's scan runs.

Usage: ``python tools/record_overhead.py [--n 100000] [--chunks 2]``.

Interpretation on the axon-relay pool: the relay serialises D2H transfers
with execution server-side (measured ~46 MB/s with zero compute overlap —
identical with plain ordering, ``copy_to_host_async``, or a fetcher
thread), so the <10% target FAILs there by environment: recorded runs pay
~26 ms per fetched MB.  On a host with a normal async transfer engine the
driver's fetch-after-next-dispatch ordering overlaps every chunk copy but
the trailing one (<2% at the 100k shape).  docs/notes.md round-5 records
the measured numbers and the diagnosis.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "experiments"))

import numpy as np

from bench import _fence, _make_sharded
from dist_svgd_tpu.utils.datasets import load_benchmark


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--chunks", type=int, default=2,
                    help="whole history chunks per trajectory")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--stepsize", type=float, default=3e-3)
    args = ap.parse_args()

    import jax

    print("devices:", jax.devices(), flush=True)
    from logreg import record_chunk_steps

    fold = load_benchmark("banana", 42)
    d = 1 + fold.x_train.shape[1]
    chunk = record_chunk_steps(args.n, d)
    niter = args.chunks * chunk
    print(f"n={args.n} d={d}: chunk={chunk} steps "
          f"({niter} steps per trajectory)", flush=True)
    sampler = _make_sharded(fold, n=args.n)

    def plain():
        out = None
        for _ in range(args.chunks):
            out = sampler.run_steps(chunk, args.stepsize)
        _fence(out)

    def recorded():
        # the driver's loop, verbatim shape (experiments/logreg.py)
        chunks, pending, final = [], None, None
        done = 0
        while done < niter:
            k = min(chunk, niter - done)
            final, hist = sampler.run_steps(k, args.stepsize, record=True)
            if pending is not None:
                chunks.append(np.asarray(pending))
            pending = hist
            done += k
        chunks.append(np.asarray(pending))
        snaps = np.concatenate(chunks + [np.asarray(final)[None]])
        assert snaps.shape == (niter + 1, sampler.num_particles, d)

    plain()      # compile, untimed
    recorded()   # compile, untimed
    best = {"plain": float("inf"), "recorded": float("inf")}
    for _ in range(args.rounds):
        for name, fn in (("plain", plain), ("recorded", recorded)):
            t0 = time.perf_counter()
            fn()
            best[name] = min(best[name], time.perf_counter() - t0)
    per_step = {k: v / niter for k, v in best.items()}
    overhead = per_step["recorded"] / per_step["plain"] - 1.0
    print(f"plain   : {per_step['plain']*1e3:8.2f} ms/step", flush=True)
    print(f"recorded: {per_step['recorded']*1e3:8.2f} ms/step "
          f"(incl. host copy of the full (niter, n, d) history)", flush=True)
    print(f"history overhead: {overhead*100:.1f}% of step time "
          f"({'PASS' if overhead < 0.10 else 'FAIL'} vs the <10% target)",
          flush=True)


if __name__ == "__main__":
    main()


