"""Worker process for ``tools/multihost_train.py``'s real-mode federation.

Not a test module and not imported by the driver — invoked as::

    python tools/multihost_worker.py --rank R --nprocs W \
        --coordinator HOST:PORT --root DIR [--devcount K] [--resume] ...

Each worker joins the ``jax.distributed`` rendezvous
(``parallel/multihost.py:initialize`` — which refuses up front on the
legacy-jax CPU-backend multiprocess gap), builds the granule-major particle
mesh spanning every process, and drives ``DistSampler`` in
``checkpoint-every``-sized segments on the absolute step grid, saving ONLY
its addressable block each segment (``state_dict`` per-process blocks) to
``<root>/step_<t>/rank_<r>``.

``--resume`` is the elastic path: the worker discovers the newest COMPLETE
step save (every rank file of the writing federation present — the saved
manifest's ``topo_process_count`` says how many), assembles the blocks
(``utils/checkpoint.py:assemble_full_state``), reshards to this
federation's mesh size (``reshard_state`` — the different-W route), and
continues from the saved step counter.  On the same layout the assembled
restore is bitwise-identical to a per-rank restore, so one code path
serves both.

On TPU hosts pass ``--devcount 0`` to keep the real platform; any positive
count forces that many virtual CPU devices (the CPU-federation mode).
"""

import argparse
import glob
import json
import os
import re
import sys
import time


def _setup_cpu(device_count: int) -> None:
    """Force this process onto ``device_count`` virtual CPU devices before
    any JAX use (the same workaround tests/_jax_env.py applies: the image
    pre-registers an ``axon`` TPU plugin that CPU-only processes must drop
    or their init blocks on the TPU tunnel)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={device_count}"
    ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from jax._src import xla_bridge

    xla_bridge._backend_factories.pop("axon", None)


def _latest_complete_save(root: str):
    """Newest ``step_<t>`` dir whose rank-file set is complete for the
    federation that wrote it; returns ``(t, [rank paths])`` or ``None``."""
    from dist_svgd_tpu.utils.checkpoint import load_state, read_manifest

    best = None
    for d in glob.glob(os.path.join(root, "step_*")):
        m = re.match(r"^step_(\d+)$", os.path.basename(d))
        if not m:
            continue
        ranks = sorted(glob.glob(os.path.join(d, "rank_*")))
        if not ranks:
            continue
        try:
            man = read_manifest(load_state(ranks[0]))
        except Exception:
            continue
        if man is None or len(ranks) != man["process_count"]:
            continue  # incomplete (a rank died mid-save) or unreadable
        t = int(m.group(1))
        if best is None or t > best[0]:
            best = (t, ranks)
    return best


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--nprocs", type=int, required=True)
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--root", required=True)
    ap.add_argument("--devcount", type=int, default=2,
                    help="virtual CPU devices per worker (0 = keep the "
                         "real platform, e.g. TPU)")
    ap.add_argument("--n", type=int, default=288)
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--checkpoint-every", type=int, default=8)
    ap.add_argument("--step-size", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--exchange-impl", choices=("gather", "ring"),
                    default="gather")
    ap.add_argument("--resume", action="store_true",
                    help="resume from the newest complete per-rank save "
                         "(assemble + reshard to this federation's size)")
    args = ap.parse_args()

    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if args.devcount > 0:
        _setup_cpu(args.devcount)

    import jax
    import numpy as np

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.gmm import gmm_logp
    from dist_svgd_tpu.parallel import multihost
    from dist_svgd_tpu.utils.checkpoint import (
        assemble_full_state,
        read_manifest,
        reshard_state,
        save_state,
    )

    gap = multihost.multiprocess_gap(args.nprocs)
    if gap is not None:  # the driver refuses earlier; workers double-check
        print(f"multihost_worker: {gap}", file=sys.stderr)
        sys.exit(3)
    assert multihost.initialize(
        coordinator_address=args.coordinator,
        num_processes=args.nprocs,
        process_id=args.rank,
    )
    assert jax.process_count() == args.nprocs

    mesh = multihost.make_particle_mesh()
    n = args.n
    start, count = multihost.process_local_rows(n, mesh)
    # same seed in every process ⇒ one well-defined global init to slice
    full = np.random.default_rng(args.seed).normal(size=(n, 2))
    full = full.astype(np.float32)
    particles = multihost.make_global_particles(
        full[start : start + count], mesh, n_global=n
    )
    ds = dt.DistSampler(
        mesh.size, lambda th, _: gmm_logp(th), None, particles,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False,
        exchange_impl="ring" if args.exchange_impl == "ring" else "gather",
        mesh=mesh,
    )

    if args.resume:
        found = _latest_complete_save(args.root)
        if found is None:
            print("multihost_worker: --resume but no complete save under "
                  f"{args.root}", file=sys.stderr)
            sys.exit(4)
        _, rank_paths = found
        state = assemble_full_state(rank_paths)
        man = read_manifest(state)
        if man is not None and man["n_shards"] != mesh.size:
            state = reshard_state(state, mesh.size)
        ds.load_state_dict(state)

    step_walls = []
    while ds.t < args.steps:
        seg = min(args.checkpoint_every, args.steps - ds.t)
        w0 = time.perf_counter()
        ds.run_steps(seg, args.step_size)
        jax.block_until_ready(ds.particles)
        step_walls.append((time.perf_counter() - w0) / seg)
        save_state(
            os.path.join(args.root, f"step_{ds.t}", f"rank_{args.rank}"),
            ds.state_dict(),
        )

    rows, r_start = multihost.host_addressable_block(ds.particles)
    np.save(os.path.join(args.root, f"final_rows_{args.rank}.npy"), rows)
    with open(os.path.join(args.root, f"done_rank{args.rank}.json"),
              "w") as fh:
        json.dump({
            "rank": args.rank,
            "nprocs": args.nprocs,
            "t": int(ds.t),
            "row_start": int(r_start),
            "rows": int(rows.shape[0]),
            "step_wall_s": float(np.median(step_walls)) if step_walls else None,
            "updates_per_s": (
                float(n / np.median(step_walls)) if step_walls else None),
            "dcn_crossings_per_hop": multihost.dcn_boundary_crossings(mesh),
        }, fh)


if __name__ == "__main__":
    main()
