"""Serving load generator: closed- and open-loop traffic against the
micro-batched predictive engine, one BENCH-style JSON row out.

Two loops because they answer different questions (classic load-gen
distinction):

- **closed loop** (`--clients` threads, each issuing its next request only
  after the previous resolves) measures sustainable throughput and the
  latency the system settles into at its own pace — coordinated omission
  included by construction, so it flatters latency under saturation;
- **open loop** (requests issued on a fixed-rate schedule regardless of
  completions, latency measured from the *scheduled* arrival) is the honest
  latency probe at a target arrival rate, and shows shed-on-overflow doing
  its job when the rate exceeds capacity.

The timed window excludes engine warm-up (every padding bucket pre-traced),
so ``recompiles`` reports steady-state bucket-cache misses — the engine's
contract is that this is 0.  The window additionally runs under
``tools/jaxlint``'s ``retrace_sentry``: ``sentry_compiles`` counts EVERY
XLA compilation inside it, not just bucket-cache misses — the counter that
caught the per-request-shape pad/slice compiles the bucket counter was
blind to (docs/notes.md round 9).  Both must be 0;
``perf_regress.py``'s ``serve_throughput`` row FAILs on either.

In-process by default (engine + batcher, no network noise — the number
``perf_regress.py``'s ``serve_throughput`` incumbent gates); ``--url`` points
the closed loop at a live ``serving.server`` instead (adds HTTP+JSON cost).

Mesh-sharded serving (round 12): ``--devices N`` shards the ensemble across
N devices (emulated on CPU hosts via
``--xla_force_host_platform_device_count``, the MULTICHIP bench pattern) and
emits the ``serve_sharded`` row — same schema plus ``devices``/``lanes``/
``dtype`` and per-lane ``lane_fairness`` counts; ``--lanes N`` runs N
batcher worker lanes over the shared engine (meaningful with or without a
mesh); ``--dtype bfloat16`` serves the low-precision kernels and stamps the
same-session ``dtype_speedup`` vs an f32 reference loop.

Multi-tenant registry (round 14): ``--tenants N`` hosts N heterogeneous
tenants (mixed logreg/BNN/GMM shapes, cycled) behind ONE
``serving.registry.ModelRegistry`` and emits the ``serve_multitenant`` row:
per-tenant rps/p50/p99 (read back from the tenant-labelled telemetry
histograms — the same series a Prometheus scrape shows), ``tenant_fairness``
(min over max per-tenant completion rate), sentry-verified ZERO cross-tenant
steady-state recompiles in the timed window, plus two deterministic
off-window drills of the protective machinery: an **eviction probe** (a cold
tenant added past the LRU bucket bound must evict exactly the
least-recently-used bucket — ``evictions`` ≥ 1) and a **quota probe** (a hog
tenant over its inflight-rows quota must shed before a polite tenant when
the bounded queue fills — ``quota_sheds`` ≥ 1).  ``perf_regress.py`` FAILs
the row on any in-window recompile and on either probe not observing its
event.

Output: one JSON row, e.g.::

    {"metric": "serve_throughput", "value": 1234.5, "unit": "requests/sec",
     "rows_per_sec": 8641.5, "p50_ms": 3.1, "p99_ms": 9.8,
     "queue_wait_p50_ms": 1.2, "device_p50_ms": 1.7,
     "batch_occupancy_mean": 7.0, "requests_per_batch_mean": 5.2,
     "recompiles": 0, "sentry_compiles": 0, "bucket_hit_rate": 1.0, "shed": 0,
     "serve_latency_p99": 9.9,
     "latency_hist_ms": {"count": 2000, "p50": 3.2, "p95": 7.1, "p99": 9.9},
     "telemetry": {"tracing": false, "queue_depth_last": 0, "shed_total": 0},
     "open_loop": {"rate_rps": 500, "achieved_rps": 499.1, "p50_ms": 2.9,
                   "p99_ms": 11.0, "shed": 0}, ...}

``serve_latency_p99`` / ``latency_hist_ms`` come from the telemetry
registry's log-spaced latency histogram over the timed window (round 10) —
the same series a Prometheus scrape of ``/metrics`` shows, bucket-
interpolated (vs the exact sorted-sample ``p50_ms``/``p99_ms``).
``--trace PATH`` additionally enables the span tracer for the window and
exports a Perfetto-loadable Chrome trace (request lane trees: one
``serve.request`` span per request with queue-wait / coalesce / dispatch
children; summarise with ``tools/trace_report.py``).  ``--ab-telemetry N``
emits the ``telemetry_overhead`` row instead (interleaved tracer-off/on
rounds; ``perf_regress.py`` FAILs it above 3%).
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dist_svgd_tpu.serving.batcher import _percentile  # noqa: E402


def build_engine(model="logreg", n_particles=10_000, n_features=54,
                 checkpoint=None, seed=0, max_bucket=256, registry=None,
                 devices=1, dtype=None):
    """Checkpointed ensemble when given, else a seeded synthetic one —
    serving throughput depends on shapes, not on convergence.

    ``devices > 1`` shards the ensemble across that many devices through
    the unified :class:`~dist_svgd_tpu.parallel.plan.Plan` (falling back
    to single-device when the host has fewer — ``make_plan``'s graceful
    degradation); ``dtype`` opts into the low-precision serve kernels.
    """
    import numpy as np

    from dist_svgd_tpu.parallel.plan import make_plan
    from dist_svgd_tpu.serving import PredictiveEngine

    plan = make_plan(devices) if devices and devices > 1 else None
    kw = dict(max_bucket=max_bucket, registry=registry, plan=plan,
              dtype=dtype)
    if checkpoint:
        source = checkpoint if len(checkpoint) > 1 else checkpoint[0]
        return PredictiveEngine.from_checkpoint(
            source, model, n_features=n_features if model == "bnn" else None,
            **kw,
        )
    rng = np.random.default_rng(seed)
    if model == "logreg":
        parts = rng.normal(size=(n_particles, 1 + n_features))
    elif model == "bnn":
        from dist_svgd_tpu.models.bnn import num_params

        parts = rng.normal(size=(n_particles, num_params(n_features)))
    else:  # gmm
        parts = rng.normal(size=(n_particles, n_features))
    return PredictiveEngine(
        model, parts.astype(np.float32),
        n_features=n_features if model == "bnn" else None,
        **kw,
    )


def _request_pool(feature_dim, rows_cycle, pool=256, seed=1):
    """Pre-generated request arrays (generation cost must not be timed)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    return [
        rng.normal(size=(rows_cycle[i % len(rows_cycle)], feature_dim))
        .astype(np.float32)
        for i in range(pool)
    ]


def request_pool_by_size(feature_dim, sizes, per_size=32, seed=1):
    """Pre-generated request arrays keyed by row count — the shared
    request-pool plumbing (round 18): ``tools/workload_replay.py`` draws
    heavy-tailed per-event sizes from a trace and picks a pre-built array
    of exactly that size here, so request generation is never on the
    replay's timed path (the same discipline ``_request_pool`` gives the
    fixed-cycle loops)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    return {int(r): [rng.normal(size=(int(r), feature_dim))
                     .astype(np.float32) for _ in range(per_size)]
            for r in sorted({int(r) for r in sizes})}


def closed_loop(submit, pool, clients, requests):
    """`clients` threads, next request only after the last resolved."""
    from dist_svgd_tpu.serving.batcher import Overloaded

    lock = threading.Lock()
    issued = [0]
    lats, shed = [], [0]

    def worker():
        while True:
            with lock:
                if issued[0] >= requests:
                    return
                i = issued[0]
                issued[0] += 1
            t0 = time.perf_counter()
            try:
                submit(pool[i % len(pool)]).result(timeout=60)
            except Overloaded:
                with lock:
                    shed[0] += 1
                continue
            lat = (time.perf_counter() - t0) * 1e3
            with lock:
                lats.append(lat)

    threads = [threading.Thread(target=worker) for _ in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lats.sort()
    return {
        "wall_s": wall,
        "completed": len(lats),
        "shed": shed[0],
        "rps": len(lats) / wall if wall > 0 else 0.0,
        "p50_ms": _percentile(lats, 0.50),
        "p99_ms": _percentile(lats, 0.99),
    }


def open_loop(submit, pool, rate_rps, requests):
    """Fixed-rate arrivals; latency from the scheduled arrival time, so a
    backed-up queue is charged to the system, not hidden by the generator
    (no coordinated omission)."""
    from dist_svgd_tpu.serving.batcher import Overloaded

    lock = threading.Lock()
    lats, shed = [], [0]
    done = threading.Semaphore(0)
    interval = 1.0 / rate_rps
    start = time.perf_counter()

    def on_done(scheduled, fut):
        lat = (time.perf_counter() - scheduled) * 1e3
        with lock:
            if fut.exception() is None:
                lats.append(lat)
        done.release()

    for i in range(requests):
        scheduled = start + i * interval
        now = time.perf_counter()
        if scheduled > now:
            time.sleep(scheduled - now)
        try:
            fut = submit(pool[i % len(pool)])
        except Overloaded:
            with lock:
                shed[0] += 1
            done.release()
            continue
        fut.add_done_callback(
            lambda f, s=max(scheduled, now): on_done(s, f)
        )
    for _ in range(requests):
        done.acquire(timeout=60)
    wall = time.perf_counter() - start
    lats.sort()
    return {
        "rate_rps": rate_rps,
        "achieved_rps": len(lats) / wall if wall > 0 else 0.0,
        "completed": len(lats),
        "shed": shed[0],
        "p50_ms": _percentile(lats, 0.50),
        "p99_ms": _percentile(lats, 0.99),
    }


def _http_submit(url):
    """Closed-loop transport for --url: one blocking HTTP round trip per
    request, dressed as a resolved future."""
    import urllib.request
    from concurrent.futures import Future

    def submit(x):
        req = urllib.request.Request(
            url.rstrip("/") + "/predict",
            json.dumps({"inputs": x.tolist()}).encode(),
            {"Content-Type": "application/json"},
        )
        body = json.loads(urllib.request.urlopen(req, timeout=60).read())
        fut = Future()
        if "outputs" in body:
            fut.set_result(body["outputs"])
        else:
            fut.set_exception(RuntimeError(body.get("error", "bad reply")))
        return fut

    return submit


def run_bench(model="logreg", n_particles=10_000, n_features=54,
              clients=16, requests=2000, rows=(1, 4, 16), max_batch=256,
              max_wait_ms=2.0, max_queue_rows=8192, open_rate=0.0,
              open_requests=500, checkpoint=None, seed=0, url=None,
              engine=None, trace=None, slo_p99_ms=100.0,
              devices=1, lanes=1, dtype=None):
    """Measure and return the JSON row (importable — perf_regress uses this).

    Mesh-sharded serving (round 12): ``devices > 1`` shards the ensemble
    across the mesh and flips the row's metric to ``serve_sharded`` (the
    row carries ``devices``/``lanes``, per-lane fairness counters, and the
    lane-labelled in-flight gauges); ``lanes`` runs that many batcher
    worker lanes over the shared engine either way.  ``dtype='bfloat16'``
    serves the low-precision kernel path and additionally measures an
    interleaved f32 reference loop on the same shapes, stamping
    ``f32_rps`` + ``dtype_speedup`` into the row.

    ``trace``: a path enables the span tracer for the timed window and
    exports a Perfetto-loadable Chrome trace there (``True`` traces without
    exporting — the overhead A/B).  ``engine``: reuse a pre-built engine
    (its warmup cost then amortises across calls — the A/B runs).

    Telemetry rows: each call uses a **fresh** ``MetricsRegistry``, so the
    histogram-derived fields (``serve_latency_p99``, ``latency_hist_ms``)
    aggregate exactly this call's timed window.

    Posterior-health fields (round 11): ``ess``/``ess_frac`` — score-free
    kernel-ESS of the served ensemble over a strided subsample
    (``telemetry.diagnostics.ensemble_health``; ``ksd`` is ``None`` here —
    serving has no ∇log p; the training-side ``fault_recovery`` row carries
    the measured KSD); ``slo_status`` — the declarative serving SLOs
    (p99 under ``slo_p99_ms``, shed/error budgets) evaluated over exactly
    this window (``perf_regress`` FAILs a breaching row);
    ``diagnostics_overhead`` — wall of the (off-request-path) health
    evaluation as a fraction of the timed window.
    """
    import jax

    from dist_svgd_tpu import telemetry
    from dist_svgd_tpu.serving import MicroBatcher

    if url:
        # url mode measures a REMOTE server: the local engine below only
        # supplies feature_dim/request shapes, so local topology flags
        # must not label the row (a serve_sharded metric has to describe
        # the engine that served the traffic, not the load generator)
        devices, lanes, dtype = 1, 1, None
    registry = telemetry.MetricsRegistry()
    prebuilt_engine = engine is not None
    if engine is None:
        engine = build_engine(model, n_particles, n_features, checkpoint,
                              seed, max_bucket=max_batch, registry=registry,
                              devices=devices, dtype=dtype)
    pool = _request_pool(engine.feature_dim, list(rows))
    plan_info = engine.stats()["plan"]
    sharded = bool(plan_info["sharded"])
    row = {
        # one metric name per serving topology: the sharded row gates
        # against its own incumbent window, not the single-device one
        "metric": "serve_sharded" if sharded else "serve_throughput",
        "unit": "requests/sec",
        "platform": jax.devices()[0].platform,
        "model": engine.model,
        "n_particles": engine.n_particles,
        "feature_dim": engine.feature_dim,
        "devices": plan_info["num_shards"],
        "lanes": lanes,
        "dtype": engine.stats()["dtype"],
        "clients": clients,
        "requests": requests,
        "rows_per_request": list(rows),
        "max_batch": max_batch,
        "max_wait_ms": max_wait_ms,
    }
    if url:
        closed = closed_loop(_http_submit(url), pool, clients, requests)
        row.update(transport="http", url=url, value=round(closed["rps"], 1),
                   p50_ms=round(closed["p50_ms"], 3),
                   p99_ms=round(closed["p99_ms"], 3), shed=closed["shed"])
        return row

    from tools.jaxlint.sentry import retrace_sentry

    engine.warmup()  # steady-state measurement: no compiles in the window
    misses_before = engine.stats()["bucket_misses"]
    batcher = MicroBatcher(
        engine.predict, max_batch=max_batch, lanes=lanes,
        max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
        registry=registry,
    )
    # tracing covers exactly the timed window (warmup compiles stay out of
    # the trace, like they stay out of the sentry count); idempotent enable
    # so an outer tracer (perf_regress) is reused, not replaced
    tracer = None
    own_tracer = False
    if trace:
        own_tracer = telemetry.get_tracer() is None
        tracer = telemetry.enable()
    try:
        with retrace_sentry("serve_bench timed window") as sentry:
            closed = closed_loop(batcher.submit, pool, clients, requests)
            open_row = None
            if open_rate > 0:
                open_row = open_loop(batcher.submit, pool, open_rate,
                                     open_requests)
    finally:
        batcher.close(drain=True)
        if tracer is not None and own_tracer:
            telemetry.disable()
    bstats = batcher.stats()
    estats = engine.stats()
    lookups = estats["bucket_hits"] + estats["bucket_misses"] - misses_before
    mean_rows = sum(rows) / len(rows)
    row.update(
        transport="inprocess",
        value=round(closed["rps"], 1),
        rows_per_sec=round(closed["rps"] * mean_rows, 1),
        wall_s=round(closed["wall_s"], 3),
        p50_ms=round(closed["p50_ms"], 3),
        p99_ms=round(closed["p99_ms"], 3),
        queue_wait_p50_ms=round(bstats["queue_wait_p50_ms"], 3),
        queue_wait_p99_ms=round(bstats["queue_wait_p99_ms"], 3),
        device_p50_ms=round(bstats["device_p50_ms"], 3),
        device_p99_ms=round(bstats["device_p99_ms"], 3),
        batch_occupancy_mean=round(bstats["batch_occupancy_mean"], 2),
        requests_per_batch_mean=round(bstats["requests_per_batch_mean"], 2),
        recompiles=estats["bucket_misses"] - misses_before,
        # independent runtime counter: EVERY XLA compile in the window
        # (bucket misses only see kernel-cache traffic)
        sentry_compiles=sentry.compiles if sentry.supported else None,
        bucket_hit_rate=round(estats["bucket_hits"] / lookups, 4)
        if lookups else 1.0,
        # closed_loop's own count, NOT plus the batcher's _n_shed — the
        # batcher increments before raising the same Overloaded the loop
        # counts, and its total also includes open-loop sheds
        shed=closed["shed"],
    )
    # registry-histogram percentiles (telemetry round 10): the request
    # latency distribution over the whole window from the shared registry's
    # log-spaced buckets — bucket-interpolated, so they cross-check the
    # exact closed-loop p50/p99 above, and they are what a Prometheus
    # scrape of a production server would show
    lat_hist = registry.histogram("svgd_serve_request_latency_seconds")
    hist_ms = lat_hist.summary(scale=1e3)
    row.update(
        serve_latency_p99=hist_ms["p99"],
        latency_hist_ms=hist_ms,
        # trace_propagation: while tracing is on, every submit mints and
        # threads an X-Fleet-Trace-style id through its lane tree (round
        # 16) — so the tracer-on arm of the telemetry-overhead A/B prices
        # propagation in, and the existing 3% ceiling stays binding
        telemetry={"tracing": bool(trace),
                   "trace_propagation": bool(trace),
                   "queue_depth_last": registry.gauge(
                       "svgd_serve_queue_depth_rows").value(
                           batcher=batcher.metrics_instance),
                   "shed_total": registry.counter(
                       "svgd_serve_shed_total").value()},
        # per-lane fairness (round 12): raw per-lane resolution counts plus
        # the lane-labelled in-flight gauges — a stuck lane shows up as a
        # starved count and a pinned nonzero gauge instead of vanishing
        # into the aggregate means
        lane_fairness={
            "lanes": lanes,
            "requests": bstats["lane_requests"],
            "batches": bstats["lane_batches"],
            "inflight_rows_last": {
                f"l{i}": registry.gauge(
                    "svgd_serve_lane_inflight_rows").value(
                        batcher=batcher.metrics_instance, lane=f"l{i}")
                for i in range(lanes)
            },
        },
    )
    if (dtype is not None and not prebuilt_engine
            and str(jax.numpy.dtype(dtype)) != "float32"):
        # low-precision satellite: an interleaved f32 reference loop on
        # the same shapes/topology (its own registry — the main row's
        # histograms stay clean), so the speedup is a same-session A/B.
        # Skipped when the caller supplied the engine (the telemetry A/B
        # reuses one warmed engine across many calls — re-measuring the
        # f32 reference each time would be pure waste)
        ref_engine = build_engine(model, n_particles, n_features,
                                  checkpoint, seed, max_bucket=max_batch,
                                  registry=telemetry.MetricsRegistry(),
                                  devices=devices, dtype=None)
        ref_engine.warmup()
        ref_batcher = MicroBatcher(
            ref_engine.predict, max_batch=max_batch, lanes=lanes,
            max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
            registry=telemetry.MetricsRegistry(),
        )
        try:
            ref = closed_loop(ref_batcher.submit, pool, clients, requests)
        finally:
            ref_batcher.close(drain=True)
        row.update(
            f32_rps=round(ref["rps"], 1),
            dtype_speedup=round(closed["rps"] / ref["rps"], 3)
            if ref["rps"] > 0 else None,
        )
    if tracer is not None:
        if isinstance(trace, str):
            n_events = tracer.export_chrome(trace)
            row["trace"] = {"path": trace, "events": n_events,
                            "dropped": tracer.dropped_events}
        else:
            row["trace"] = {"events": len(tracer.chrome_events()),
                            "dropped": tracer.dropped_events}
    if open_row is not None:
        row["open_loop"] = {k: round(v, 3) if isinstance(v, float) else v
                            for k, v in open_row.items()}

    # posterior-health + SLO stamp (round 11): ensemble diagnostics are
    # score-free at serve time and run OFF the request path; the first
    # (compile-bearing) health call is warmed untimed so the reported
    # overhead is the steady-state cost relative to the timed window
    from dist_svgd_tpu.telemetry.diagnostics import ensemble_health
    from dist_svgd_tpu.telemetry.slo import default_serving_slos

    ensemble_health(engine.particles, max_points=1024)  # warm (compiles)
    t_diag0 = time.perf_counter()
    health = ensemble_health(engine.particles, max_points=1024)
    diag_wall = time.perf_counter() - t_diag0
    slo_doc = default_serving_slos(
        registry, p99_ms=slo_p99_ms).evaluate()
    row.update(
        ksd=None,  # no score function at serve time (schema parity with
                   # the fault_recovery row, which measures it in training)
        ess=round(health["ess"], 2),
        ess_frac=round(health["ess_frac"], 4),
        slo_status=slo_doc["status"],
        slo={name: {"status": o["status"], "burn_rate": o["burn_rate"]}
             for name, o in slo_doc["objectives"].items()},
        diagnostics_overhead=round(
            diag_wall / max(closed["wall_s"] + diag_wall, 1e-9), 4),
    )
    return row


def measure_telemetry_overhead(rounds=3, **kw):
    """A/B the span tracer's cost on the closed-loop bench: interleaved
    disabled/enabled rounds over ONE warmed engine, best-of each arm (the
    same noise discipline as perf_regress's interleaved rounds — a host
    slowdown hits both arms of a round together).  Returns the
    ``telemetry_overhead`` row; the CI gate FAILs it above 3%.
    """
    kw.pop("engine", None)
    kw.pop("trace", None)
    engine = build_engine(
        kw.get("model", "logreg"), kw.get("n_particles", 10_000),
        kw.get("n_features", 54), kw.get("checkpoint"), kw.get("seed", 0),
        max_bucket=kw.get("max_batch", 256),
        devices=kw.get("devices", 1), dtype=kw.get("dtype"),
    )
    engine.warmup()
    best = {"off": 0.0, "on": 0.0}
    for _ in range(rounds):
        off = run_bench(engine=engine, trace=None, **kw)
        on = run_bench(engine=engine, trace=True, **kw)
        best["off"] = max(best["off"], off["value"])
        best["on"] = max(best["on"], on["value"])
    overhead = (1.0 - best["on"] / best["off"]) if best["off"] > 0 else 0.0
    return {
        "metric": "telemetry_overhead",
        "rounds": rounds,
        "rps_disabled": round(best["off"], 1),
        "rps_enabled": round(best["on"], 1),
        "overhead_frac": round(overhead, 4),
    }


#: Mixed-shape tenant cycle for --tenants N: model kind, ensemble size, and
#: feature width all vary so no two neighbouring tenants share an XLA
#: program (the cross-tenant-churn test is only honest on heterogeneous
#: shapes).
def _tenant_specs(n_tenants):
    from dist_svgd_tpu.models.bnn import num_params

    specs = []
    for i in range(n_tenants):
        kind = ("logreg", "bnn", "gmm")[i % 3]
        if kind == "logreg":
            nf = (54, 24, 96)[(i // 3) % 3]
            specs.append(dict(name=f"logreg-{i}", model="logreg",
                              n_particles=2048 + 512 * ((i // 3) % 3),
                              d=1 + nf, feature_dim=nf))
        elif kind == "bnn":
            nf = (8, 16)[(i // 3) % 2]
            specs.append(dict(name=f"bnn-{i}", model="bnn",
                              n_particles=192 + 64 * ((i // 3) % 2),
                              d=num_params(nf), feature_dim=nf,
                              engine_kw=dict(n_features=nf)))
        else:
            dim = (8, 16, 32)[(i // 3) % 3]
            specs.append(dict(name=f"gmm-{i}", model="gmm",
                              n_particles=1024 + 256 * ((i // 3) % 3),
                              d=dim, feature_dim=dim))
    return specs


def _quota_probe(seed=3):
    """Deterministic drill of the quota shed-priority path on a paused
    registry batcher: a hog tenant fills the bounded queue past its
    inflight-rows quota, then a polite tenant's arrival must shed the
    hog's newest queued request (not the polite one).  Untimed and
    isolated (own metrics registry) — the machinery check the
    ``serve_multitenant`` row records as ``quota_sheds``."""
    import numpy as np

    from dist_svgd_tpu import telemetry
    from dist_svgd_tpu.serving import ModelRegistry

    rng = np.random.default_rng(seed)
    probe = ModelRegistry(
        metrics=telemetry.MetricsRegistry(), max_total_buckets=4,
        max_batch=8, max_queue_rows=32, batcher_autostart=False,
    )
    nf = 4
    parts = rng.normal(size=(32, 1 + nf)).astype(np.float32)
    probe.add_tenant("hog", "logreg", particles=parts, min_bucket=8,
                     max_bucket=8, quota_rows=8)
    probe.add_tenant("polite", "logreg", particles=parts.copy(),
                     min_bucket=8, max_bucket=8)
    x = rng.normal(size=(8, nf)).astype(np.float32)
    hog_futs = [probe.batcher.submit(x, tenant="hog") for _ in range(4)]
    polite_fut = probe.batcher.submit(x, tenant="polite")
    stats = probe.batcher.stats()
    probe.batcher.start()
    polite_ok = polite_fut.result(timeout=30) is not None
    hog_shed = sum(1 for f in hog_futs
                   if f.done() and f.exception() is not None)
    probe.close(drain=True)
    return {
        "quota_sheds": int(sum(stats["quota_sheds"].values())),
        "per_tenant": stats["quota_sheds"],
        "hog_requests_shed": hog_shed,
        "polite_served": polite_ok,
    }


def run_multitenant_bench(tenants=10, clients=16, requests=2000,
                          rows=(1, 4, 16), max_batch=256, max_wait_ms=2.0,
                          max_queue_rows=8192, lanes=1, seed=0,
                          max_total_buckets=None):
    """Measure the multi-tenant registry and return the
    ``serve_multitenant`` JSON row (importable — perf_regress uses this).

    ``max_total_buckets`` defaults to EXACTLY the working set (tenants ×
    buckets the request sizes touch): the timed window then runs with a
    full-but-not-overflowing LRU — zero steady-state recompiles — and the
    post-window eviction probe (one cold tenant added past the bound)
    deterministically observes the first eviction.
    """
    import jax
    import numpy as np

    from dist_svgd_tpu import telemetry
    from dist_svgd_tpu.serving import ModelRegistry
    from dist_svgd_tpu.serving.engine import bucket_for
    from tools.jaxlint.sentry import retrace_sentry

    rows = tuple(rows)
    min_bucket = 8
    working_buckets = len({bucket_for(r, min_bucket) for r in rows})
    cap = (max_total_buckets if max_total_buckets is not None
           else tenants * working_buckets)
    metrics = telemetry.MetricsRegistry()
    rng = np.random.default_rng(seed)
    reg = ModelRegistry(
        metrics=metrics, max_total_buckets=cap, max_batch=max_batch,
        lanes=lanes, max_wait_ms=max_wait_ms, max_queue_rows=max_queue_rows,
    )
    specs = _tenant_specs(tenants)
    pools = {}
    for spec in specs:
        parts = rng.normal(size=(spec["n_particles"], spec["d"]))
        reg.add_tenant(
            spec["name"], spec["model"],
            particles=parts.astype(np.float32),
            min_bucket=min_bucket, max_bucket=max_batch,
            **spec.get("engine_kw", {}),
        )
        pools[spec["name"]] = _request_pool(
            spec["feature_dim"], list(rows), pool=64,
            seed=seed + 1 + len(pools))
    names = [s["name"] for s in specs]
    reg.warm(rows)  # steady state: every reachable bucket pre-traced
    misses_before = {
        n: reg.tenant(n).engine.stats()["bucket_misses"] for n in names}

    # closed loop, tenants round-robin: every tenant sees the same offered
    # load, so per-tenant completion rates measure fairness, not the
    # generator's bias
    lock = threading.Lock()
    issued = [0]
    lats = {n: [] for n in names}
    shed = [0]

    from dist_svgd_tpu.serving.batcher import Overloaded

    def worker():
        while True:
            with lock:
                if issued[0] >= requests:
                    return
                i = issued[0]
                issued[0] += 1
            name = names[i % len(names)]
            pool = pools[name]
            t0 = time.perf_counter()
            try:
                reg.submit(name, pool[i % len(pool)]).result(timeout=60)
            except Overloaded:
                with lock:
                    shed[0] += 1
                continue
            lat = (time.perf_counter() - t0) * 1e3
            with lock:
                lats[name].append(lat)

    threads = [threading.Thread(target=worker) for _ in range(clients)]
    with retrace_sentry("serve_multitenant timed window") as sentry:
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0

    recompiles = sum(
        reg.tenant(n).engine.stats()["bucket_misses"] - misses_before[n]
        for n in names)
    lat_hist = metrics.histogram("svgd_serve_request_latency_seconds")
    per_tenant = {}
    tenant_rps = {}
    for spec in specs:
        n = spec["name"]
        tl = sorted(lats[n])
        hist = lat_hist.summary(scale=1e3, tenant=n)
        rps = len(tl) / wall if wall > 0 else 0.0
        tenant_rps[n] = rps
        per_tenant[n] = {
            "model": spec["model"],
            "n_particles": spec["n_particles"],
            "feature_dim": spec["feature_dim"],
            "requests": len(tl),
            "rps": round(rps, 1),
            "p50_ms": round(_percentile(tl, 0.50), 3),
            "p99_ms": round(_percentile(tl, 0.99), 3),
            "hist_p99_ms": hist["p99"],
        }
    all_lats = sorted(v for ls in lats.values() for v in ls)
    completed = len(all_lats)
    fairness = (min(tenant_rps.values()) / max(tenant_rps.values())
                if tenant_rps and max(tenant_rps.values()) > 0 else 0.0)

    # --- eviction probe (off-window): one cold tenant past the LRU bound
    # must evict exactly one least-recently-used bucket; the window above
    # already proved the hot working set never recompiled
    evictions_before = reg.kernel_cache.stats()["evictions"]
    probe_parts = rng.normal(size=(64, 9)).astype(np.float32)
    reg.add_tenant("evict-probe", "logreg", particles=probe_parts,
                   min_bucket=min_bucket, max_bucket=max_batch)
    reg.predict("evict-probe", rng.normal(size=(1, 8)).astype(np.float32))
    cache_stats = reg.kernel_cache.stats()
    eviction_probe = {
        "evictions_before": evictions_before,
        "evictions_after": cache_stats["evictions"],
        "cache_size": cache_stats["size"],
    }
    reg.close(drain=True)

    quota_probe = _quota_probe(seed=seed + 7)

    return {
        "metric": "serve_multitenant",
        "unit": "requests/sec",
        "platform": jax.devices()[0].platform,
        "tenants": tenants,
        "clients": clients,
        "requests": requests,
        "rows_per_request": list(rows),
        "max_batch": max_batch,
        "max_wait_ms": max_wait_ms,
        "lanes": lanes,
        "value": round(completed / wall, 1) if wall > 0 else 0.0,
        "wall_s": round(wall, 3),
        "completed": completed,
        "shed": shed[0],
        "p50_ms": round(_percentile(all_lats, 0.50), 3),
        "p99_ms": round(_percentile(all_lats, 0.99), 3),
        "p99_worst_tenant_ms": max(
            (pt["p99_ms"] for pt in per_tenant.values()), default=0.0),
        "tenant_fairness": round(fairness, 4),
        "per_tenant": per_tenant,
        "recompiles": recompiles,
        "sentry_compiles": sentry.compiles if sentry.supported else None,
        "kernel_cache": cache_stats,
        "evictions": cache_stats["evictions"],
        "eviction_probe": eviction_probe,
        "quota_sheds": quota_probe["quota_sheds"],
        "quota_probe": quota_probe,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=("logreg", "bnn", "gmm"), default="logreg")
    ap.add_argument("--n-particles", type=int, default=10_000)
    ap.add_argument("--n-features", type=int, default=54,
                    help="feature width (logreg/bnn inputs; gmm particle dim)")
    ap.add_argument("--checkpoint", action="append", default=None,
                    help="serve a real ensemble (repeatable for one "
                         "multi-host save); default is a seeded synthetic one")
    ap.add_argument("--devices", type=int, default=1,
                    help="shard the served ensemble across this many "
                         "devices and emit the serve_sharded row; on a "
                         "CPU host the devices are emulated "
                         "(--xla_force_host_platform_device_count, the "
                         "MULTICHIP bench pattern)")
    ap.add_argument("--tenants", type=int, default=0,
                    help="host this many mixed-shape tenants behind one "
                         "ModelRegistry and emit the serve_multitenant "
                         "row instead (ignores --model/--n-particles/"
                         "--devices/--dtype)")
    ap.add_argument("--max-total-buckets", type=int, default=None,
                    help="multi-tenant LRU bound on compiled kernel "
                         "buckets across tenants (default: exactly the "
                         "working set, so the eviction probe evicts "
                         "deterministically)")
    ap.add_argument("--lanes", type=int, default=1,
                    help="batcher dispatch worker lanes over the shared "
                         "engine")
    ap.add_argument("--dtype", choices=("float32", "bfloat16"), default=None,
                    help="serve-kernel compute dtype; bfloat16 also "
                         "measures the f32 reference loop and stamps "
                         "dtype_speedup into the row")
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--requests", type=int, default=2000)
    ap.add_argument("--rows", default="1,4,16",
                    help="comma-separated request sizes, cycled")
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--max-queue-rows", type=int, default=8192)
    ap.add_argument("--open-rate", type=float, default=0.0,
                    help="also run an open loop at this requests/sec (0 = off)")
    ap.add_argument("--open-requests", type=int, default=500)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--url", default=None,
                    help="closed-loop against a live serving.server "
                         "instead of in-process")
    ap.add_argument("--slo-p99-ms", type=float, default=100.0,
                    help="serve-p99 SLO threshold stamped into the row's "
                         "slo_status (perf_regress FAILs a breaching row)")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="enable the span tracer for the timed window and "
                         "export a Perfetto-loadable Chrome trace here "
                         "(summarise with tools/trace_report.py)")
    ap.add_argument("--ab-telemetry", type=int, default=0, metavar="ROUNDS",
                    help="instead of one bench row, A/B the tracer's "
                         "overhead over this many interleaved "
                         "disabled/enabled rounds")
    args = ap.parse_args()

    if args.devices > 1:
        # host device emulation, the MULTICHIP bench pattern: must land in
        # the environment before the first backend client initialises (no
        # jax device call has happened yet — imports alone don't init).
        # The flag only affects the host (CPU) platform; a real TPU host
        # keeps its real devices and the flag is inert.
        import re as _re

        flags = _re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        )
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()

    rows = tuple(int(r) for r in args.rows.split(","))
    kw = dict(
        model=args.model, n_particles=args.n_particles,
        n_features=args.n_features, clients=args.clients,
        requests=args.requests, rows=rows, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, max_queue_rows=args.max_queue_rows,
        open_rate=args.open_rate, open_requests=args.open_requests,
        checkpoint=args.checkpoint, seed=args.seed,
        devices=args.devices, lanes=args.lanes, dtype=args.dtype,
    )
    if args.tenants:
        out = run_multitenant_bench(
            tenants=args.tenants, clients=args.clients,
            requests=args.requests, rows=rows, max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms, max_queue_rows=args.max_queue_rows,
            lanes=args.lanes, seed=args.seed,
            max_total_buckets=args.max_total_buckets,
        )
    elif args.ab_telemetry:
        out = measure_telemetry_overhead(rounds=args.ab_telemetry, **kw)
    else:
        out = run_bench(url=args.url, trace=args.trace,
                        slo_p99_ms=args.slo_p99_ms, **kw)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
