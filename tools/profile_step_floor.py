"""Decompose the small-config scan-step floor (VERDICT r2 #3).

After the round-2 kernel work, every small config sits on a ~0.6–0.9 ms/step
floor (config 1: 100 particles × 100 iters = 0.056 s → 0.56 ms/step) that is
not φ compute.  This tool separates the two candidate components:

- **per-dispatch cost** — host→device latency of one ``run_steps``/scan
  dispatch through the axon tunnel (paid once per call, amortised by longer
  scans): measured by timing the same body at several iters-per-dispatch;
- **per-iteration cost** — the compiled scan body itself (paid per step,
  invariant to dispatch length): the asymptote of ms/step as the dispatch
  grows.

and then builds the config-1 step up component by component (empty body →
score only → φ only → full step) at the asymptotic dispatch length, so the
per-iteration floor's composition is measured rather than guessed.

Usage: ``python tools/profile_step_floor.py [--n 100]``.

``--jax-trace DIR`` wraps the measured sections in
``utils/metrics.py:profiler_trace`` (``jax.profiler.trace``), so a
TensorBoard/Perfetto-readable **device** trace of the exact dispatches being
timed is one flag away — the device-side complement to the host-side span
tracer (``dist_svgd_tpu/telemetry``); load ``DIR`` in TensorBoard's profile
plugin or ``xprof``.
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "experiments"))
from paths import DATA_DIR  # noqa: F401  (bootstraps sys.path)

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dist_svgd_tpu.models.logreg import logreg_logp
from dist_svgd_tpu.ops.kernels import RBF
from dist_svgd_tpu.ops.pallas_svgd import resolve_phi_fn
from dist_svgd_tpu.utils.metrics import profiler_trace
from dist_svgd_tpu.utils.rng import as_key, init_particles
from dist_svgd_tpu.utils.datasets import load_benchmark


def timed_scan(body, particles, iters, reps=3, samples=3):
    """bench.py protocol: compile untimed, then best-of-``samples`` where each
    sample is ``reps`` state-chained dispatches under one scalar fetch."""

    @jax.jit
    def run(p):
        out, _ = lax.scan(lambda parts, i: (body(parts, i), None),
                          p, jnp.arange(iters))
        return out

    np.asarray(run(particles))  # warm/compile, full fetch
    best = float("inf")
    for _ in range(samples):
        out = particles
        t0 = time.perf_counter()
        for _ in range(reps):
            out = run(out)
        np.asarray(out)[0, 0]
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def donate_ab(n: int, iters: int = 100, chain: int = 32, samples: int = 3,
              seed: int = 0) -> dict:
    """Donated-vs-undonated A/B of the training-scan carry (ROADMAP item 1:
    the step carries donate through the single Plan compile site).  Two
    identical samplers — ``donate_carries`` on/off — run the same chained
    ``run()`` schedule; the record carries both walls, the ratio, and the
    **bitwise** agreement of the final particle arrays (donation is pure
    buffer aliasing: any numeric difference is a bug)."""
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.utils.datasets import load_benchmark as _lb

    fold = _lb("banana", 42)
    x = jnp.asarray(fold.x_train)
    t = jnp.asarray(fold.t_train.reshape(-1))
    d = 1 + x.shape[1]
    logp = lambda th: logreg_logp(th, (x, t))
    walls, finals = {}, {}
    for donate in (True, False):
        s = dt.Sampler(d, logp, donate_carries=donate)
        out = init_particles(seed, n, d)
        out, _ = s.run(n, iters, 3e-3, seed=seed, record=False,
                       initial_particles=out)  # compile, untimed
        best = float("inf")
        for _ in range(samples):
            t0 = time.perf_counter()
            for _ in range(chain):
                out, _ = s.run(n, iters, 3e-3, seed=seed, record=False,
                               initial_particles=out)
            np.asarray(out)[0, 0]
            best = min(best, (time.perf_counter() - t0) / chain)
        walls[donate] = best
        finals[donate] = np.asarray(out)
    return {
        "bench": "donate_ab", "n": n, "iters_per_dispatch": iters,
        "chain": chain,
        "donated_ms_per_dispatch": round(walls[True] * 1e3, 4),
        "undonated_ms_per_dispatch": round(walls[False] * 1e3, 4),
        "speedup": round(walls[False] / walls[True], 4),
        "bitwise_equal": bool(np.array_equal(finals[True], finals[False])),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--jax-trace", default=None, metavar="DIR",
                    help="capture a jax.profiler device trace of the "
                         "measured sections into DIR (TensorBoard/xprof-"
                         "readable); off when omitted")
    ap.add_argument("--donate-ab", action="store_true",
                    help="measure the donated-vs-undonated training-carry "
                         "A/B (identical schedules, donate_carries on/off) "
                         "and pin the final states bitwise; skips the "
                         "floor decomposition")
    args = ap.parse_args()

    if args.donate_ab:
        import json

        row = donate_ab(args.n)
        print(json.dumps(row), flush=True)
        if not row["bitwise_equal"]:
            raise SystemExit("donation changed the numerics — bug")
        return

    print("devices:", jax.devices(), flush=True)
    fold = load_benchmark("banana", 42)
    x = jnp.asarray(fold.x_train)
    t = jnp.asarray(fold.t_train.reshape(-1))
    d = 1 + x.shape[1]
    P0 = init_particles(0, args.n, d)
    eps = jnp.float32(3e-3)
    phi_fn = resolve_phi_fn(RBF(1.0), "auto")
    batched_score = jax.vmap(
        jax.grad(logreg_logp, argnums=0), in_axes=(0, None)
    )
    key = as_key(0)

    bodies = {
        # pure scan floor: one elementwise op per iteration
        "empty (axpy only)": lambda p, i: p * jnp.float32(1.0 + 1e-7),
        # + per-step PRNG fold (what a minibatch config pays even pre-draw)
        "fold_in + axpy": lambda p, i: p * (
            1.0 + 1e-7 * jax.random.fold_in(key, i)[0].astype(jnp.float32)
        ),
        "score only": lambda p, i: p + eps * batched_score(p, (x, t)),
        "phi only": lambda p, i: p + eps * phi_fn(p, p, p),
        "full step (score+phi)": lambda p, i: p + eps * phi_fn(
            p, p, batched_score(p, (x, t))
        ),
    }

    print(f"\nconfig-1 shape: n={args.n}, d={d}, rows={x.shape[0]}")
    print(f"{'body':26s} " + "".join(f"{k:>10d}it" for k in (100, 1000)))
    asym = {}
    # device trace of the measured dispatches, one flag away (module
    # docstring) — a no-op context when --jax-trace is omitted
    with profiler_trace(args.jax_trace):
        for name, body in bodies.items():
            walls = []
            for iters in (100, 1000):
                w = timed_scan(body, P0, iters, reps=args.reps)
                walls.append(w / iters * 1e3)
            asym[name] = walls[-1]
            print(f"{name:26s} " + "".join(f"{w:11.4f}" for w in walls)
                  + "   ms/step", flush=True)

    print("\nper-iteration composition at the 1000-iter dispatch:")
    base = asym["empty (axpy only)"]
    for name, v in asym.items():
        print(f"  {name:26s} {v:8.4f} ms/step  (+{v - base:7.4f} over empty)")

    # --- the decisive measurement: marginal cost per dispatch ------------
    # One fenced sample costs a FIXED ~0.06-0.1 s round trip (dispatch RPC +
    # scalar fetch) regardless of workload; chained dispatches pipeline.
    # Sweeping the chain length separates the fixed round trip from the
    # marginal per-dispatch cost — at config-1 scale the marginal cost of a
    # full 100-step dispatch measures ~0.2 ms (~2 us/step), i.e. the
    # round-2 "0.56 ms/step floor" was >=95% measurement round trip, not
    # framework.  bench.py's _timed_chain sizes its chain adaptively off
    # this fact (reps=None).
    full = bodies["full step (score+phi)"]

    @jax.jit
    def run100(p):
        out, _ = lax.scan(lambda parts, i: (full(parts, i), None),
                          p, jnp.arange(100))
        return out

    np.asarray(run100(P0))  # compile
    print("\nchain-length sweep, full 100-step config-1 dispatches:")
    prev_total = None
    with profiler_trace(args.jax_trace):
        for chain in (1, 8, 32, 128):
            best = float("inf")
            for _ in range(3):
                out = P0
                t0 = time.perf_counter()
                for _ in range(chain):
                    out = run100(out)
                np.asarray(out)[0, 0]
                best = min(best, time.perf_counter() - t0)
            line = (f"  chain={chain:4d}: {best*1e3:9.1f} ms total, "
                    f"{best/chain*1e3:8.3f} ms/dispatch, "
                    f"{args.n*100/(best/chain):12.0f} up/s")
            if prev_total is not None:
                marg = (best - prev_total[1]) / (chain - prev_total[0])
                line += f"   marginal {marg*1e3:7.3f} ms/dispatch"
            print(line, flush=True)
            prev_total = (chain, best)


if __name__ == "__main__":
    main()
