"""Fleet status plane: one-shot fleet view from a running FleetRouter.

Polls the router's ``/fleet`` route (``FleetRouter.fleet_status()``: one
federation sweep over the live replicas, then breaker states, per-tenant
fleet-wide counters/percentiles from the **merged** histograms, and the
SLO verdicts over the federated window) and renders a human table or one
JSON document.

Per-tenant **rps** needs a window, which a one-shot CLI doesn't have — so
the tool polls ``/fleet`` twice, ``--interval-s`` apart, and derives each
tenant's fleet-wide rate from the federated request-counter delta (the
counters are restart-clamped upstream, so a replica bouncing between the
two polls can only under-count, never go negative).  ``--interval-s 0``
skips the second poll (rates report ``null``).

The same two-poll delta drives the **cost columns** (round 23): per
tenant, fleet-wide device-seconds/s (the fraction of one device the
tenant is burning) and rows/s from the federated ``svgd_usage_*``
counters riding the ``/fleet`` tenants rows; and per replica, the same
two rates from the router's ``/usage`` per-replica breakdown (polled at
the same two instants).  Replicas without usage metering contribute
nothing and the columns print ``-``.

Usage::

    python tools/fleet_status.py --url http://127.0.0.1:8100
    python tools/fleet_status.py --url http://127.0.0.1:8100 --json
    python tools/fleet_status.py --url ... --interval-s 2.0

Exit codes: 0 healthy (some replica closed, SLO not breaching), 1 when
the fleet is degraded or an SLO is burning, 2 when the router is
unreachable or answers garbage — so the CLI slots into shell health
checks as-is.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Tuple


def fetch_fleet(url: str, timeout_s: float = 5.0) -> Dict[str, Any]:
    """GET ``<url>/fleet`` and return the parsed status document."""
    req = urllib.request.Request(url.rstrip("/") + "/fleet")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        doc = json.loads(resp.read())
    if not isinstance(doc, dict) or "replicas" not in doc:
        raise ValueError("reply is not a fleet status document")
    return doc


def fetch_usage(url: str, timeout_s: float = 5.0
                ) -> Optional[Dict[str, Any]]:
    """GET ``<url>/usage`` (the router's federated cost summary), or
    ``None`` against a router without the route."""
    req = urllib.request.Request(url.rstrip("/") + "/usage")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            doc = json.loads(resp.read())
    except urllib.error.HTTPError:
        return None
    return doc if isinstance(doc, dict) else None


def _rate(cur: Optional[float], prev: Optional[float],
          interval_s: float) -> Optional[float]:
    if cur is None or prev is None or interval_s <= 0:
        return None
    return max(float(cur) - float(prev), 0.0) / interval_s


def derive_rates(first: Dict[str, Any], second: Dict[str, Any],
                 interval_s: float) -> Dict[str, Dict[str, Optional[float]]]:
    """Per-tenant fleet rates from the two polls' federated counters
    (non-negative by construction — the federation clamps restarts):
    ``{tenant: {rps, device_s_per_s, rows_per_s}}``."""
    rates: Dict[str, Dict[str, Optional[float]]] = {}
    t0 = first.get("tenants", {})
    for name, row in second.get("tenants", {}).items():
        prev = t0.get(name) or {}
        rates[name] = {
            "rps": _rate(row.get("requests_total"),
                         prev.get("requests_total"), interval_s),
            "device_s_per_s": _rate(row.get("device_seconds_total"),
                                    prev.get("device_seconds_total"),
                                    interval_s),
            "rows_per_s": _rate(row.get("usage_rows_total"),
                                prev.get("usage_rows_total"), interval_s),
        }
    return rates


def derive_replica_rates(first: Optional[Dict[str, Any]],
                         second: Optional[Dict[str, Any]],
                         interval_s: float
                         ) -> Dict[str, Dict[str, Optional[float]]]:
    """Per-replica cost rates from two ``/usage`` polls' per-replica
    breakdowns, summed over tenants: ``{replica: {device_s_per_s,
    rows_per_s}}``."""
    if not first or not second:
        return {}

    def _totals(doc):
        out: Dict[str, Dict[str, float]] = {}
        for rid, tenants in (doc.get("replicas") or {}).items():
            agg = {"device_seconds": 0.0, "rows": 0.0}
            for row in tenants.values():
                agg["device_seconds"] += float(row.get("device_seconds", 0.0))
                agg["rows"] += float(row.get("rows", 0))
            out[rid] = agg
        return out

    prev, cur = _totals(first), _totals(second)
    return {
        rid: {
            "device_s_per_s": _rate(agg["device_seconds"],
                                    (prev.get(rid) or {}).get(
                                        "device_seconds"), interval_s),
            "rows_per_s": _rate(agg["rows"],
                                (prev.get(rid) or {}).get("rows"),
                                interval_s),
        }
        for rid, agg in cur.items()
    }


def build_report(first: Dict[str, Any], second: Optional[Dict[str, Any]],
                 interval_s: float,
                 usage_first: Optional[Dict[str, Any]] = None,
                 usage_second: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """The tool's JSON document: the latest status doc plus derived
    per-tenant rates (requests + cost) and a one-word health verdict."""
    doc = second if second is not None else first
    rates = (derive_rates(first, second, interval_s)
             if second is not None else {})
    replica_rates = derive_replica_rates(usage_first, usage_second,
                                         interval_s)
    slo_status = (doc.get("slo") or {}).get("status")
    healthy = bool(doc.get("replicas_closed")) and slo_status != "breach"

    def _round(v, nd):
        return None if v is None else round(v, nd)

    tenants = {}
    for name, row in doc.get("tenants", {}).items():
        r = rates.get(name) or {}
        tenants[name] = {
            **row,
            "rps": _round(r.get("rps"), 2),
            "device_s_per_s": _round(r.get("device_s_per_s"), 4),
            "rows_per_s": _round(r.get("rows_per_s"), 1),
        }
    return {
        "metric": "fleet_status",
        "healthy": healthy,
        "status": doc.get("status"),
        "slo_status": slo_status,
        "replicas_closed": doc.get("replicas_closed"),
        "replicas_total": doc.get("replicas_total"),
        "replicas": {rid: {"state": st.get("state"),
                           "reason": st.get("reason"),
                           "ejections": st.get("ejections"),
                           "generation": st.get("generation"),
                           "last_healthy_age_s": st.get(
                               "last_healthy_age_s"),
                           "device_s_per_s": _round(
                               (replica_rates.get(rid) or {}).get(
                                   "device_s_per_s"), 4),
                           "rows_per_s": _round(
                               (replica_rates.get(rid) or {}).get(
                                   "rows_per_s"), 1)}
                     for rid, st in (doc.get("replicas") or {}).items()},
        "federation": doc.get("federation"),
        "tenants": tenants,
        "slo": doc.get("slo"),
        "interval_s": interval_s if second is not None else 0.0,
        "ts": doc.get("ts"),
    }


def render(report: Dict[str, Any]) -> str:
    out = [f"fleet: {report['status']}  "
           f"({report['replicas_closed']}/{report['replicas_total']} "
           f"replicas closed; slo {report['slo_status']})"]
    out.append("replicas:")
    for rid in sorted(report["replicas"]):
        st = report["replicas"][rid]
        line = f"  {rid:12s} {st['state']:9s}"
        if st.get("generation") is not None:
            # per-replica serving generation: a mid-rollout fleet shows
            # which replicas already flipped to the new posterior
            line += f" gen={st['generation']}"
        if st.get("reason"):
            line += f" reason={st['reason']}"
        if st.get("ejections"):
            line += f" ejections={st['ejections']}"
        if st.get("last_healthy_age_s") is not None:
            line += f" last_healthy={st['last_healthy_age_s']}s ago"
        if st.get("device_s_per_s") is not None:
            line += f" dev_s/s={st['device_s_per_s']:.4f}"
        if st.get("rows_per_s") is not None:
            line += f" rows/s={st['rows_per_s']:.1f}"
        out.append(line)
    fed = report.get("federation") or {}
    line = (f"federation: {fed.get('scrapes', 0)} sweeps, last "
            f"{fed.get('last_scrape_ms')} ms, monotone="
            f"{fed.get('monotone')}")
    if fed.get("scrape_errors"):
        line += f", errors={fed['scrape_errors']}"
    out.append(line)
    tenants = report.get("tenants") or {}
    if tenants:
        name_w = max([len(n) for n in tenants] + [6])
        out.append(f"{'tenant':{name_w}s} {'requests':>9s} {'rps':>8s} "
                   f"{'p50ms':>9s} {'p99ms':>9s} {'dev_s/s':>9s} "
                   f"{'rows/s':>9s}")
        for name in sorted(tenants):
            t = tenants[name]
            rps = "-" if t.get("rps") is None else f"{t['rps']:.1f}"
            dev = ("-" if t.get("device_s_per_s") is None
                   else f"{t['device_s_per_s']:.4f}")
            rows = ("-" if t.get("rows_per_s") is None
                    else f"{t['rows_per_s']:.1f}")
            out.append(
                f"{name:{name_w}s} {t.get('requests', 0):9d} {rps:>8s} "
                f"{t.get('p50_ms', 0.0):9.3f} {t.get('p99_ms', 0.0):9.3f} "
                f"{dev:>9s} {rows:>9s}")
    slo = (report.get("slo") or {}).get("objectives") or {}
    if slo:
        out.append("slo objectives:")
        for name in sorted(slo):
            o = slo[name]
            out.append(f"  {name:18s} {o.get('status', '?'):8s} "
                       f"burn={o.get('burn_rate')}")
    return "\n".join(out)


def collect(url: str, interval_s: float, timeout_s: float = 5.0
            ) -> Tuple[Dict[str, Any], Optional[Dict[str, Any]],
                       Optional[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """Both polls, each pairing ``/fleet`` with ``/usage`` (the latter
    tolerated missing) so status and per-replica cost share a window."""
    first = fetch_fleet(url, timeout_s=timeout_s)
    usage_first = fetch_usage(url, timeout_s=timeout_s)
    second = usage_second = None
    if interval_s > 0:
        time.sleep(interval_s)
        second = fetch_fleet(url, timeout_s=timeout_s)
        usage_second = fetch_usage(url, timeout_s=timeout_s)
    return first, second, usage_first, usage_second


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", required=True,
                    help="the FleetRouter's base URL (its /fleet route)")
    ap.add_argument("--interval-s", type=float, default=1.0,
                    help="window between the two /fleet polls that the "
                         "per-tenant rps derives from (0 = single poll, "
                         "no rates)")
    ap.add_argument("--timeout-s", type=float, default=5.0)
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON document")
    args = ap.parse_args(argv)
    try:
        first, second, usage_first, usage_second = collect(
            args.url, args.interval_s, timeout_s=args.timeout_s)
    except (urllib.error.URLError, OSError, ValueError,
            json.JSONDecodeError) as e:
        print(f"fleet_status: cannot read {args.url}/fleet: {e}",
              file=sys.stderr)
        return 2
    report = build_report(first, second, args.interval_s,
                          usage_first=usage_first,
                          usage_second=usage_second)
    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    return 0 if report["healthy"] else 1


if __name__ == "__main__":
    sys.exit(main())
