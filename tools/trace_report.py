"""Summarise a telemetry trace file: per-span percentiles, self-time,
compile events.

Reads either exporter format the tracer writes
(``dist_svgd_tpu/telemetry/trace.py``):

- **Chrome trace JSON** (``Tracer.export_chrome`` — the Perfetto-loadable
  ``{"traceEvents": [...]}`` document, µs timestamps), or
- **JSONL** (one record per completed span/instant through ``JsonlLogger``,
  second timestamps, ``kind`` field).

and prints, per span name: count, p50/p95/p99/max duration, total wall, and
total **self-time** (duration minus time covered by child spans on the same
track — the "where did the time actually go" number a nested trace hides);
plus the top-N self-time ranking and every ``xla_compile`` instant bucketed
by the span it fired inside (a compile inside ``serve.dispatch`` in steady
state is a retrace bug — the runtime cousin of ``tools/jaxlint``'s sentry).

``--postmortem`` instead renders a **flight-recorder bundle**
(``telemetry.FlightRecorder.dump`` — written when a guard trips, a fault
fires, the restart budget exhausts, or a hot reload is rejected): the
header's reason and context, the last posterior-diagnostics report, the
metric snapshot, and the ring of events leading up to the dump.

``--stitch router.json replica*.json`` (round 16) joins **multiple
per-process exports into one tree per request**: every export carries a
process-identity header (role/name/pid) plus a wall↔monotonic clock
anchor, and every routed request carries one trace id across the
``X-Fleet-Trace`` hop — so the router's ``fleet.route ⊃ fleet.attempt``
lane trees and each replica's ``serve.request ⊃ …`` trees reassemble as
``fleet.route ⊃ fleet.attempt ⊃ [fleet.wire gap] ⊃ serve.request ⊃ …``,
with retries/hedges as sibling attempts and the derived network/queue
gap surfaced as the synthetic ``fleet.wire`` span.  The report carries
per-hop p50/p95/p99, the **stitch coverage** fraction (served routes that
found their replica tree — the fleet drill gates this at 1.0 in fake
mode), and the orphan count (replica traces whose router export is
missing — reported, never crashing).

``--programs`` (round 23) renders the **per-program cost attribution**
view instead of a span summary: the top programs by fenced dispatch
self-time from the ``svgd_prog_*`` series the dispatch profiler
(``telemetry/profile.py``) writes — per ``plan://<label>`` identity:
dispatches, total seconds, mean ms, share of attributed wall, rows and
input bytes.  Input is a saved ``MetricsRegistry.dump()`` JSON (e.g. a
``/metrics.dump`` fetch) or a telemetry **history directory**
(``telemetry/history.py`` ring), whose window deltas are summed.

A missing, empty, or corrupt input — including a stitch export without a
process header or clock anchor — exits with one line on stderr and a
nonzero status (2) — no tracebacks from the CLI.

Usage::

    python tools/trace_report.py trace.json           # human table
    python tools/trace_report.py trace.json --json    # machine row
    python tools/trace_report.py serve.jsonl --top 5
    python tools/trace_report.py postmortem_001_guard_violation.jsonl --postmortem
    python tools/trace_report.py --stitch router.json replica0.json replica1.json
    python tools/trace_report.py --programs metrics_dump.json
    python tools/trace_report.py --programs telemetry_history_dir/ --top 5
"""

import argparse
import json
import os
import sys


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def load_export(path):
    """Normalise either trace format to ``(process, spans, instants)``:
    ``process`` is the export's process-identity header (role/name/pid +
    clock anchor; ``None`` for pre-round-16 exports), spans are
    ``{name, ts_us, dur_us, tid, args}`` and instants
    ``{name, ts_us, tid, args}``."""
    process = None
    with open(path) as fh:
        first = fh.readline()
        fh.seek(0)
        # both formats start with "{": a Chrome doc is ONE object with
        # "traceEvents" (export_chrome writes it on one line; other
        # producers pretty-print, making the first line unparseable alone),
        # a JSONL file is one flat record per line
        try:
            doc0 = json.loads(first)
            is_chrome = isinstance(doc0, dict) and "traceEvents" in doc0
        except json.JSONDecodeError:
            is_chrome = True
        if is_chrome:
            doc = json.load(fh)
            raw = doc.get("traceEvents", [])
            other = doc.get("otherData")
            if isinstance(other, dict) and isinstance(
                    other.get("process"), dict):
                process = other["process"]
        else:  # JSONL: one span/instant record per line
            raw = []
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                kind = rec.get("kind")
                if kind == "process":
                    process = rec  # last wins (set_process rewrites it)
                    continue
                if kind not in ("span", "instant"):
                    continue
                ev = {"name": rec["name"], "ph": "X" if kind == "span" else "i",
                      "ts": rec["ts"] * 1e6, "tid": rec.get("tid", 0),
                      "args": rec.get("args")}
                if kind == "span":
                    ev["dur"] = rec.get("dur", 0.0) * 1e6
                raw.append(ev)
    spans, instants = [], []
    for ev in raw:
        ph = ev.get("ph")
        if ph == "X":
            spans.append({"name": ev["name"], "ts_us": float(ev["ts"]),
                          "dur_us": float(ev.get("dur", 0.0)),
                          "tid": ev.get("tid", 0),
                          "args": ev.get("args") or {}})
        elif ph == "i":
            instants.append({"name": ev["name"], "ts_us": float(ev["ts"]),
                             "tid": ev.get("tid", 0),
                             "args": ev.get("args") or {}})
    return process, spans, instants


def load_events(path):
    """Back-compat single-file loader: ``(spans, instants)``."""
    _, spans, instants = load_export(path)
    return spans, instants


def _self_times(spans):
    """Per-span self-time: duration minus the duration of child spans on the
    same track (direct children only — grandchildren are already subtracted
    from their own parent).  Containment nesting per tid, the trace-viewer
    convention."""
    self_us = [s["dur_us"] for s in spans]
    by_tid = {}
    for i, s in enumerate(spans):
        by_tid.setdefault(s["tid"], []).append(i)
    # ts and dur are rounded independently at export (0.001 µs), so an
    # adjacent sibling can appear to start marginally before the previous
    # span's computed end — the epsilon keeps it a sibling, not a child
    # (a genuine child overlaps by far more than 10 ns)
    eps = 0.01
    for indices in by_tid.values():
        # start ascending; ties: longest first so the outer span parents
        indices.sort(key=lambda i: (spans[i]["ts_us"], -spans[i]["dur_us"]))
        stack = []  # indices of currently-open spans
        for i in indices:
            ts = spans[i]["ts_us"]
            while stack and (spans[stack[-1]]["ts_us"]
                             + spans[stack[-1]]["dur_us"]) <= ts + eps:
                stack.pop()
            if stack:
                self_us[stack[-1]] -= spans[i]["dur_us"]
            stack.append(i)
    return self_us


def _enclosing(spans_by_tid, instant):
    """Name of the innermost span containing the instant on its track (the
    exporter also tags instants with ``in_span`` at record time — preferred
    when present, since thread-stack context beats timestamp containment)."""
    arg = instant["args"].get("in_span")
    if arg:
        return arg
    best, best_dur = None, None
    for s in spans_by_tid.get(instant["tid"], ()):
        if s["ts_us"] <= instant["ts_us"] <= s["ts_us"] + s["dur_us"]:
            if best_dur is None or s["dur_us"] < best_dur:
                best, best_dur = s["name"], s["dur_us"]
    return best or "(no span)"


def summarize(spans, instants, top=10):
    """The report dict (``main`` renders it; tests consume it directly)."""
    self_us = _self_times(spans)
    by_name = {}
    for i, s in enumerate(spans):
        entry = by_name.setdefault(s["name"], {"durs": [], "self_us": 0.0})
        entry["durs"].append(s["dur_us"])
        entry["self_us"] += self_us[i]
    rows = {}
    for name, entry in by_name.items():
        durs = sorted(entry["durs"])
        rows[name] = {
            "count": len(durs),
            "p50_ms": round(_percentile(durs, 0.50) / 1e3, 4),
            "p95_ms": round(_percentile(durs, 0.95) / 1e3, 4),
            "p99_ms": round(_percentile(durs, 0.99) / 1e3, 4),
            "max_ms": round(durs[-1] / 1e3, 4),
            "total_ms": round(sum(durs) / 1e3, 3),
            "self_ms": round(entry["self_us"] / 1e3, 3),
        }
    top_self = sorted(rows, key=lambda n: -rows[n]["self_ms"])[:top]
    spans_by_tid = {}
    for s in spans:
        spans_by_tid.setdefault(s["tid"], []).append(s)
    compiles = [i for i in instants if i["name"] == "xla_compile"]
    compile_spans = {}
    for inst in compiles:
        where = _enclosing(spans_by_tid, inst)
        compile_spans[where] = compile_spans.get(where, 0) + 1
    return {
        "spans": rows,
        "top_self": top_self,
        "n_spans": len(spans),
        "n_instants": len(instants),
        "compiles": len(compiles),
        "compile_spans": compile_spans,
    }


def render(report):
    rows = report["spans"]
    name_w = max([len(n) for n in rows] + [4])
    out = [f"{'span':{name_w}s} {'count':>7s} {'p50ms':>9s} {'p95ms':>9s} "
           f"{'p99ms':>9s} {'max ms':>9s} {'total ms':>10s} {'self ms':>10s}"]
    for name in sorted(rows, key=lambda n: -rows[n]["total_ms"]):
        r = rows[name]
        out.append(
            f"{name:{name_w}s} {r['count']:7d} {r['p50_ms']:9.3f} "
            f"{r['p95_ms']:9.3f} {r['p99_ms']:9.3f} {r['max_ms']:9.3f} "
            f"{r['total_ms']:10.2f} {r['self_ms']:10.2f}"
        )
    out.append("")
    out.append("top self-time: " + ", ".join(
        f"{n} ({rows[n]['self_ms']:.2f} ms)" for n in report["top_self"]))
    out.append(f"xla compiles: {report['compiles']}")
    for where, n in sorted(report["compile_spans"].items(), key=lambda kv: -kv[1]):
        out.append(f"  {n:4d} in {where}")
    return "\n".join(out)


# --------------------------------------------------------------------- #
# cross-process stitching (round 16)


def _require_anchor(path, process):
    """The stitch contract: every export must self-identify and carry the
    wall↔monotonic anchor — without it cross-process timestamps cannot be
    aligned, and guessing would silently mis-attribute the wire gap."""
    if not isinstance(process, dict):
        raise ValueError(
            f"{path} carries no process-identity header — re-export it "
            "with the current tracer (Chrome otherData.process / JSONL "
            "kind=\"process\" record)")
    for field in ("anchor_unix_s", "anchor_trace_s"):
        if not isinstance(process.get(field), (int, float)):
            raise ValueError(
                f"{path} has no clock anchor ({field}) in its process "
                "header — cross-process timestamps cannot be aligned")


def stitch_files(paths, wire_span="fleet.wire"):
    """Join router + replica trace exports into one tree per request.

    ``paths``: one or more router exports (process role ``"router"``) plus
    any number of replica exports, in any order — files self-identify via
    their process headers.  Returns the stitch report dict (``main``
    renders it; ``tools/fleet_drill.py`` reads ``coverage`` off it):

    - ``coverage`` — fraction of *served* router routes whose serving
      attempt matched a replica ``serve.request`` tree on the trace id
      (the fleet drill's fake-mode gate is exactly 1.0);
    - ``orphan_replica_traces`` — replica-side traces with no router
      route (a missing/rotated router export): reported, never fatal;
    - ``hops`` — per-hop duration percentiles across all stitched trees,
      including the synthetic ``fleet.wire`` span (the attempt wall not
      covered by the replica's serve span, on the anchor-aligned wall
      clock: network + replica HTTP queueing);
    - ``trees`` — one record per served route, retries/hedges as sibling
      attempts.
    """
    exports = []
    for path in paths:
        process, spans, _instants = load_export(path)
        _require_anchor(path, process)
        exports.append({"path": path, "process": process, "spans": spans})
    routers = [e for e in exports if e["process"].get("role") == "router"]
    replicas = [e for e in exports if e["process"].get("role") != "router"]
    if not routers:
        raise ValueError(
            "no export identifies as the router (process role "
            "\"router\") — pass the router's trace alongside the replicas'")

    def wall_us(export, ts_us):
        p = export["process"]
        return (p["anchor_unix_s"] - p["anchor_trace_s"]) * 1e6 + ts_us

    # routes keyed trace -> LIST: a client may replay one X-Fleet-Trace
    # id across requests (the front door passes it through verbatim), and
    # collapsing those onto one tree would corrupt attempt/coverage
    # accounting — each fleet.route span stays its own tree, and its
    # attempts bind to it by time containment within the route interval
    routes = {}
    attempts = {}
    for e in routers:
        for s in e["spans"]:
            trace = s["args"].get("trace")
            if not trace:
                continue
            if s["name"] == "fleet.route":
                routes.setdefault(trace, []).append(
                    {"span": s, "export": e})
            elif s["name"] == "fleet.attempt":
                attempts.setdefault(trace, []).append(
                    {"span": s, "export": e})
    serves = {}
    for e in replicas:
        by_tid = {}
        for s in e["spans"]:
            by_tid.setdefault(s["tid"], []).append(s)
        for s in e["spans"]:
            if s["name"] != "serve.request":
                continue
            trace = s["args"].get("trace")
            if not trace:
                continue
            # the tree's children share the lane track and nest inside
            # the parent interval (lane allocation guarantees no overlap
            # between trees; the 1 µs epsilon absorbs export rounding)
            children = [c for c in by_tid[s["tid"]]
                        if c is not s
                        and c["ts_us"] >= s["ts_us"] - 1.0
                        and (c["ts_us"] + c["dur_us"]
                             <= s["ts_us"] + s["dur_us"] + 1.0)]
            serves.setdefault(trace, []).append({
                "span": s, "export": e, "children": children,
                "replica": (s["args"].get("replica")
                            or e["process"].get("name"))})

    hop_durs = {}

    def add_hop(name, dur_us):
        hop_durs.setdefault(name, []).append(dur_us)

    trees = []
    eligible = 0
    stitched = 0
    retry_trees = 0
    hedged_trees = 0
    n_routes = sum(len(lst) for lst in routes.values())
    route_records = sorted(
        ((trace, r) for trace, lst in routes.items() for r in lst),
        key=lambda tr: tr[1]["span"]["ts_us"])
    serve_used: dict = {}
    for trace, route in route_records:
        rspan = route["span"]
        rargs = rspan["args"]
        if rargs.get("outcome") != "served":
            continue  # sheds / unroutables / deadlines owe no replica tree
        eligible += 1
        add_hop("fleet.route", rspan["dur_us"])
        rt0 = rspan["ts_us"]
        rt1 = rt0 + rspan["dur_us"]
        atts = sorted((a for a in attempts.get(trace, [])
                       if rt0 - 1.0 <= a["span"]["ts_us"]
                       and (a["span"]["ts_us"] + a["span"]["dur_us"]
                            <= rt1 + 1.0)),
                      key=lambda a: a["span"]["ts_us"])
        serve_list = sorted(serves.get(trace, []),
                            key=lambda s: s["span"]["ts_us"])
        # one consumed-serve-span pool per trace, shared across any
        # duplicate-id routes, so a serve tree matches exactly one attempt
        used = serve_used.setdefault(trace, set())
        tree_attempts = []
        matched_any = False
        for a in atts:
            aspan = a["span"]
            aargs = aspan["args"]
            add_hop("fleet.attempt", aspan["dur_us"])
            rec = {"n": aargs.get("n"), "replica": aargs.get("replica"),
                   "dur_ms": round(aspan["dur_us"] / 1e3, 4)}
            if aargs.get("hedged"):
                rec["hedged"] = True
            if "error" in aargs:
                rec["error"] = aargs["error"]
                tree_attempts.append(rec)
                continue
            rec["status"] = aargs.get("status")
            match = None
            for i, sv in enumerate(serve_list):
                if i not in used and sv["replica"] == aargs.get("replica"):
                    match = (i, sv)
                    break
            if match is None:
                tree_attempts.append(rec)
                continue
            i, sv = match
            used.add(i)
            matched_any = True
            sspan = sv["span"]
            add_hop("serve.request", sspan["dur_us"])
            for c in sv["children"]:
                add_hop(c["name"], c["dur_us"])
            a_start = wall_us(a["export"], aspan["ts_us"])
            a_end = a_start + aspan["dur_us"]
            s_start = wall_us(sv["export"], sspan["ts_us"])
            s_end = s_start + sspan["dur_us"]
            gap_us = max(s_start - a_start, 0.0) + max(a_end - s_end, 0.0)
            add_hop(wire_span, gap_us)
            rec["serve"] = {"replica": sv["replica"],
                            "dur_ms": round(sspan["dur_us"] / 1e3, 4),
                            "wire_gap_ms": round(gap_us / 1e3, 4)}
            tree_attempts.append(rec)
        if matched_any:
            stitched += 1
            if len(atts) > 1:
                retry_trees += 1
            if any(t.get("hedged") for t in tree_attempts):
                hedged_trees += 1
        trees.append({"trace": trace, "tenant": rargs.get("tenant"),
                      "status": rargs.get("status"),
                      "replica": rargs.get("replica"),
                      "stitched": matched_any,
                      "attempts": tree_attempts})
    orphans = sorted(t for t in serves if t not in routes)
    hops = {}
    for name, durs in hop_durs.items():
        durs = sorted(durs)
        hops[name] = {
            "count": len(durs),
            "p50_ms": round(_percentile(durs, 0.50) / 1e3, 4),
            "p95_ms": round(_percentile(durs, 0.95) / 1e3, 4),
            "p99_ms": round(_percentile(durs, 0.99) / 1e3, 4),
            "max_ms": round(durs[-1] / 1e3, 4) if durs else 0.0,
        }
    return {
        "files": [{"path": e["path"],
                   "role": e["process"].get("role"),
                   "name": e["process"].get("name")} for e in exports],
        "router_routes": n_routes,
        "served_routes": eligible,
        "stitched": stitched,
        "coverage": round(stitched / eligible, 6) if eligible else 1.0,
        "orphan_replica_traces": len(orphans),
        "retry_trees": retry_trees,
        "hedged_trees": hedged_trees,
        "hops": hops,
        "trees": trees,
    }


def render_stitch(report, top=10):
    out = [f"stitched {report['stitched']}/{report['served_routes']} served "
           f"routes (coverage {report['coverage']:.4f}) across "
           f"{len(report['files'])} exports; "
           f"{report['orphan_replica_traces']} orphan replica trace(s); "
           f"{report['retry_trees']} tree(s) with retries, "
           f"{report['hedged_trees']} hedged"]
    hops = report["hops"]
    if hops:
        name_w = max([len(n) for n in hops] + [4])
        out.append(f"{'hop':{name_w}s} {'count':>7s} {'p50ms':>9s} "
                   f"{'p95ms':>9s} {'p99ms':>9s} {'max ms':>9s}")
        order = ["fleet.route", "fleet.attempt", "fleet.wire",
                 "serve.request"]
        names = [n for n in order if n in hops] + sorted(
            n for n in hops if n not in order)
        for name in names:
            h = hops[name]
            out.append(f"{name:{name_w}s} {h['count']:7d} {h['p50_ms']:9.3f} "
                       f"{h['p95_ms']:9.3f} {h['p99_ms']:9.3f} "
                       f"{h['max_ms']:9.3f}")
    shown = [t for t in report["trees"] if t["stitched"]][:top]
    for t in shown:
        out.append(f"trace {t['trace']} tenant={t['tenant']} "
                   f"status={t['status']}:")
        for a in t["attempts"]:
            leg = (f"  attempt {a['n']} -> {a['replica']} "
                   f"({a['dur_ms']:.3f} ms)")
            if "error" in a:
                leg += f" {a['error']}"
            elif "serve" in a:
                leg += (f" = {a['status']}; serve.request "
                        f"{a['serve']['dur_ms']:.3f} ms, wire gap "
                        f"{a['serve']['wire_gap_ms']:.3f} ms")
            else:
                leg += f" = {a.get('status')}"
            if a.get("hedged"):
                leg += " [hedged]"
            out.append(leg)
    return "\n".join(out)


def load_postmortem(path):
    """Parse a flight-recorder bundle (JSONL): returns
    ``(header, metrics_snapshot, diagnostics, events)``.  Raises
    ``ValueError`` when the file is not a postmortem bundle."""
    header = None
    snapshot = None
    diagnostics = None
    events = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError(f"line {lineno} is not a JSON object")
            kind = rec.get("kind")
            if lineno == 1:
                if kind != "postmortem":
                    raise ValueError(
                        "first record is not a postmortem header "
                        f"(kind={kind!r}) — is this a flight-recorder "
                        "bundle?")
                header = rec
            elif kind == "metrics":
                snapshot = rec.get("snapshot")
            elif kind == "diagnostics":
                diagnostics = rec
            else:
                events.append(rec)
    if header is None:
        raise ValueError("empty file")
    return header, snapshot, diagnostics, events


def render_postmortem(header, snapshot, diagnostics, events, top=10):
    out = [f"postmortem: {header.get('reason', '?')}",
           f"  dumped at unix {header.get('ts')}; "
           f"{len(events)} ring events"]
    ctx = header.get("context") or {}
    for k in sorted(ctx):
        out.append(f"  context.{k} = {ctx[k]}")
    if diagnostics is not None:
        out.append("last diagnostics:")
        for k in sorted(diagnostics):
            if k not in ("kind", "ts"):
                out.append(f"  {k} = {diagnostics[k]}")
    if snapshot:
        out.append(f"metrics snapshot ({len(snapshot)} series):")
        for k in sorted(snapshot):
            out.append(f"  {k} = {snapshot[k]}")
    if events:
        out.append(f"ring (oldest first, last {min(len(events), top)} shown):")
        for rec in events[-top:]:
            kind = rec.get("kind", "?")
            name = rec.get("name") or rec.get("reason") or ""
            extra = {k: v for k, v in rec.items()
                     if k not in ("kind", "name", "ts")}
            out.append(f"  [{rec.get('ts', 0):>12.6f}] {kind:11s} {name} "
                       f"{extra if extra else ''}".rstrip())
    return "\n".join(out)


#: The dispatch profiler's metric names (telemetry/profile.py) — read
#: from dump documents here so the tool stays importable without jax.
_PROG_SECONDS = "svgd_prog_dispatch_seconds"
_PROG_ROWS = "svgd_prog_dispatch_rows_total"
_PROG_BYTES = "svgd_prog_dispatch_bytes_total"


def load_program_dumps(path):
    """The dump documents behind one ``--programs`` input: a metrics
    dump JSON file → ``[dump]``; a telemetry history directory → every
    record's window delta (summed downstream)."""
    if os.path.isdir(path):
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from dist_svgd_tpu.telemetry.history import TelemetryHistory

        records = TelemetryHistory(path).records()
        if not records:
            raise ValueError("no telemetry history records in directory")
        return [rec.get("window", {}) for rec in records]
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "metrics" not in doc:
        raise ValueError("not a MetricsRegistry.dump() document")
    return [doc]


def program_rows(dumps):
    """Per-label attribution rows summed over ``dumps``, sorted by total
    dispatch seconds (descending).  Federated ``replica``-labelled
    series are skipped — the rollup series already carry the total."""
    agg = {}
    for dump in dumps:
        metrics = dump.get("metrics", {})
        for name, key in ((_PROG_SECONDS, None), (_PROG_ROWS, "rows"),
                          (_PROG_BYTES, "bytes")):
            for s in (metrics.get(name) or {}).get("series", []):
                labels = s.get("labels") or {}
                if "replica" in labels:
                    continue
                label = labels.get("label", "")
                row = agg.setdefault(label, {
                    "label": label, "dispatches": 0, "seconds": 0.0,
                    "rows": 0, "bytes": 0,
                })
                if key is None:  # the histogram: sum + count
                    row["seconds"] += float(s.get("sum", 0.0) or 0.0)
                    row["dispatches"] += int(s.get("count", 0) or 0)
                else:
                    row[key] += int(s.get("value", 0) or 0)
    rows = sorted(agg.values(), key=lambda r: -r["seconds"])
    total = sum(r["seconds"] for r in rows)
    for r in rows:
        r["mean_ms"] = (1e3 * r["seconds"] / r["dispatches"]
                        if r["dispatches"] else 0.0)
        r["share"] = (r["seconds"] / total) if total > 0 else 0.0
    return {"metric": "program_attribution", "windows": len(dumps),
            "total_seconds": total, "programs": rows}


def render_programs(report, top=10):
    rows = report["programs"][:top]
    out = [f"program attribution: {len(report['programs'])} programs, "
           f"{report['total_seconds']:.4f} s attributed over "
           f"{report['windows']} window(s)"]
    if not rows:
        return (out[0] + " (no svgd_prog_* series — was the dispatch "
                "profiler enabled?)")
    label_w = max([len(r["label"]) for r in rows] + [7])
    out.append(f"{'program':{label_w}s} {'disp':>8s} {'total_s':>10s} "
               f"{'mean_ms':>9s} {'share':>7s} {'rows':>12s} {'MB':>10s}")
    for r in rows:
        out.append(
            f"{r['label']:{label_w}s} {r['dispatches']:8d} "
            f"{r['seconds']:10.4f} {r['mean_ms']:9.3f} "
            f"{100 * r['share']:6.1f}% {r['rows']:12d} "
            f"{r['bytes'] / 1e6:10.2f}")
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="+",
                    help="Chrome trace JSON (Tracer.export_chrome), "
                         "tracer JSONL file, (with --postmortem) a "
                         "flight-recorder bundle, or (with --stitch) the "
                         "router export plus every replica export")
    ap.add_argument("--top", type=int, default=10,
                    help="entries in the self-time ranking (or postmortem "
                         "ring events / stitched trees shown)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON document")
    ap.add_argument("--postmortem", action="store_true",
                    help="render a flight-recorder postmortem bundle "
                         "instead of a span summary")
    ap.add_argument("--stitch", action="store_true",
                    help="join router + replica exports into one tree per "
                         "request on the X-Fleet-Trace ids (files "
                         "self-identify via their process headers)")
    ap.add_argument("--programs", action="store_true",
                    help="render the dispatch profiler's per-program cost "
                         "attribution (input: a metrics dump JSON or a "
                         "telemetry history directory) instead of a span "
                         "summary")
    args = ap.parse_args(argv)
    if sum((args.stitch, args.postmortem, args.programs)) > 1:
        ap.error("--stitch, --postmortem and --programs are mutually "
                 "exclusive")
    if not args.stitch and len(args.trace) != 1:
        ap.error("exactly one trace file expected (pass --stitch to join "
                 "several exports)")
    trace_path = args.trace[0]

    if args.programs:
        try:
            report = program_rows(load_program_dumps(trace_path))
        except OSError as e:
            print(f"trace_report: cannot read {e.filename or trace_path}: "
                  f"{e.strerror or e}", file=sys.stderr)
            return 2
        except (json.JSONDecodeError, UnicodeDecodeError, ValueError,
                TypeError) as e:
            print(f"trace_report: {trace_path} is not a metrics dump or "
                  f"telemetry history: {e}", file=sys.stderr)
            return 2
        if args.json:
            doc = dict(report)
            doc["programs"] = doc["programs"][:args.top]
            print(json.dumps(doc))
        else:
            print(render_programs(report, top=args.top))
        return 0

    try:
        if args.stitch:
            stitch_report = stitch_files(args.trace)
        elif args.postmortem:
            header, snapshot, diagnostics, events = load_postmortem(
                trace_path)
        else:
            spans, instants = load_events(trace_path)
    except OSError as e:
        print(f"trace_report: cannot read {e.filename or trace_path}: "
              f"{e.strerror or e}", file=sys.stderr)
        return 2
    except (json.JSONDecodeError, UnicodeDecodeError, KeyError, ValueError,
            TypeError) as e:
        # corrupt/truncated JSON, a non-trace file, a malformed record, a
        # stitch export missing its process/anchor header: one clear
        # line, no traceback
        if args.stitch:
            print(f"trace_report: inputs are not a stitchable export set: "
                  f"{e}", file=sys.stderr)
        else:
            kind = ("postmortem bundle" if args.postmortem
                    else "trace file")
            print(f"trace_report: {trace_path} is not a readable {kind}: "
                  f"{e}", file=sys.stderr)
        return 2

    if args.stitch:
        if args.json:
            doc = dict(stitch_report)
            doc["trees"] = doc["trees"][:args.top]
            print(json.dumps(doc))
        else:
            print(render_stitch(stitch_report, top=args.top))
        return 0
    if args.postmortem:
        if args.json:
            print(json.dumps({"header": header, "metrics": snapshot,
                              "diagnostics": diagnostics, "events": events}))
        else:
            print(render_postmortem(header, snapshot, diagnostics, events,
                                    top=args.top))
        return 0
    if not spans and not instants:
        print(f"trace_report: no trace events in {trace_path}",
              file=sys.stderr)
        return 1
    report = summarize(spans, instants, top=args.top)
    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
