"""Summarise a telemetry trace file: per-span percentiles, self-time,
compile events.

Reads either exporter format the tracer writes
(``dist_svgd_tpu/telemetry/trace.py``):

- **Chrome trace JSON** (``Tracer.export_chrome`` — the Perfetto-loadable
  ``{"traceEvents": [...]}`` document, µs timestamps), or
- **JSONL** (one record per completed span/instant through ``JsonlLogger``,
  second timestamps, ``kind`` field).

and prints, per span name: count, p50/p95/p99/max duration, total wall, and
total **self-time** (duration minus time covered by child spans on the same
track — the "where did the time actually go" number a nested trace hides);
plus the top-N self-time ranking and every ``xla_compile`` instant bucketed
by the span it fired inside (a compile inside ``serve.dispatch`` in steady
state is a retrace bug — the runtime cousin of ``tools/jaxlint``'s sentry).

``--postmortem`` instead renders a **flight-recorder bundle**
(``telemetry.FlightRecorder.dump`` — written when a guard trips, a fault
fires, the restart budget exhausts, or a hot reload is rejected): the
header's reason and context, the last posterior-diagnostics report, the
metric snapshot, and the ring of events leading up to the dump.

A missing, empty, or corrupt input exits with one line on stderr and a
nonzero status (2) — no tracebacks from the CLI.

Usage::

    python tools/trace_report.py trace.json           # human table
    python tools/trace_report.py trace.json --json    # machine row
    python tools/trace_report.py serve.jsonl --top 5
    python tools/trace_report.py postmortem_001_guard_violation.jsonl --postmortem
"""

import argparse
import json
import sys


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def load_events(path):
    """Normalise either trace format to ``(spans, instants)`` where spans are
    ``{name, ts_us, dur_us, tid}`` and instants ``{name, ts_us, tid, args}``."""
    with open(path) as fh:
        first = fh.readline()
        fh.seek(0)
        # both formats start with "{": a Chrome doc is ONE object with
        # "traceEvents" (export_chrome writes it on one line; other
        # producers pretty-print, making the first line unparseable alone),
        # a JSONL file is one flat record per line
        try:
            doc0 = json.loads(first)
            is_chrome = isinstance(doc0, dict) and "traceEvents" in doc0
        except json.JSONDecodeError:
            is_chrome = True
        if is_chrome:
            doc = json.load(fh)
            raw = doc.get("traceEvents", [])
        else:  # JSONL: one span/instant record per line
            raw = []
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                kind = rec.get("kind")
                if kind not in ("span", "instant"):
                    continue
                ev = {"name": rec["name"], "ph": "X" if kind == "span" else "i",
                      "ts": rec["ts"] * 1e6, "tid": rec.get("tid", 0),
                      "args": rec.get("args")}
                if kind == "span":
                    ev["dur"] = rec.get("dur", 0.0) * 1e6
                raw.append(ev)
    spans, instants = [], []
    for ev in raw:
        ph = ev.get("ph")
        if ph == "X":
            spans.append({"name": ev["name"], "ts_us": float(ev["ts"]),
                          "dur_us": float(ev.get("dur", 0.0)),
                          "tid": ev.get("tid", 0)})
        elif ph == "i":
            instants.append({"name": ev["name"], "ts_us": float(ev["ts"]),
                             "tid": ev.get("tid", 0),
                             "args": ev.get("args") or {}})
    return spans, instants


def _self_times(spans):
    """Per-span self-time: duration minus the duration of child spans on the
    same track (direct children only — grandchildren are already subtracted
    from their own parent).  Containment nesting per tid, the trace-viewer
    convention."""
    self_us = [s["dur_us"] for s in spans]
    by_tid = {}
    for i, s in enumerate(spans):
        by_tid.setdefault(s["tid"], []).append(i)
    # ts and dur are rounded independently at export (0.001 µs), so an
    # adjacent sibling can appear to start marginally before the previous
    # span's computed end — the epsilon keeps it a sibling, not a child
    # (a genuine child overlaps by far more than 10 ns)
    eps = 0.01
    for indices in by_tid.values():
        # start ascending; ties: longest first so the outer span parents
        indices.sort(key=lambda i: (spans[i]["ts_us"], -spans[i]["dur_us"]))
        stack = []  # indices of currently-open spans
        for i in indices:
            ts = spans[i]["ts_us"]
            while stack and (spans[stack[-1]]["ts_us"]
                             + spans[stack[-1]]["dur_us"]) <= ts + eps:
                stack.pop()
            if stack:
                self_us[stack[-1]] -= spans[i]["dur_us"]
            stack.append(i)
    return self_us


def _enclosing(spans_by_tid, instant):
    """Name of the innermost span containing the instant on its track (the
    exporter also tags instants with ``in_span`` at record time — preferred
    when present, since thread-stack context beats timestamp containment)."""
    arg = instant["args"].get("in_span")
    if arg:
        return arg
    best, best_dur = None, None
    for s in spans_by_tid.get(instant["tid"], ()):
        if s["ts_us"] <= instant["ts_us"] <= s["ts_us"] + s["dur_us"]:
            if best_dur is None or s["dur_us"] < best_dur:
                best, best_dur = s["name"], s["dur_us"]
    return best or "(no span)"


def summarize(spans, instants, top=10):
    """The report dict (``main`` renders it; tests consume it directly)."""
    self_us = _self_times(spans)
    by_name = {}
    for i, s in enumerate(spans):
        entry = by_name.setdefault(s["name"], {"durs": [], "self_us": 0.0})
        entry["durs"].append(s["dur_us"])
        entry["self_us"] += self_us[i]
    rows = {}
    for name, entry in by_name.items():
        durs = sorted(entry["durs"])
        rows[name] = {
            "count": len(durs),
            "p50_ms": round(_percentile(durs, 0.50) / 1e3, 4),
            "p95_ms": round(_percentile(durs, 0.95) / 1e3, 4),
            "p99_ms": round(_percentile(durs, 0.99) / 1e3, 4),
            "max_ms": round(durs[-1] / 1e3, 4),
            "total_ms": round(sum(durs) / 1e3, 3),
            "self_ms": round(entry["self_us"] / 1e3, 3),
        }
    top_self = sorted(rows, key=lambda n: -rows[n]["self_ms"])[:top]
    spans_by_tid = {}
    for s in spans:
        spans_by_tid.setdefault(s["tid"], []).append(s)
    compiles = [i for i in instants if i["name"] == "xla_compile"]
    compile_spans = {}
    for inst in compiles:
        where = _enclosing(spans_by_tid, inst)
        compile_spans[where] = compile_spans.get(where, 0) + 1
    return {
        "spans": rows,
        "top_self": top_self,
        "n_spans": len(spans),
        "n_instants": len(instants),
        "compiles": len(compiles),
        "compile_spans": compile_spans,
    }


def render(report):
    rows = report["spans"]
    name_w = max([len(n) for n in rows] + [4])
    out = [f"{'span':{name_w}s} {'count':>7s} {'p50ms':>9s} {'p95ms':>9s} "
           f"{'p99ms':>9s} {'max ms':>9s} {'total ms':>10s} {'self ms':>10s}"]
    for name in sorted(rows, key=lambda n: -rows[n]["total_ms"]):
        r = rows[name]
        out.append(
            f"{name:{name_w}s} {r['count']:7d} {r['p50_ms']:9.3f} "
            f"{r['p95_ms']:9.3f} {r['p99_ms']:9.3f} {r['max_ms']:9.3f} "
            f"{r['total_ms']:10.2f} {r['self_ms']:10.2f}"
        )
    out.append("")
    out.append("top self-time: " + ", ".join(
        f"{n} ({rows[n]['self_ms']:.2f} ms)" for n in report["top_self"]))
    out.append(f"xla compiles: {report['compiles']}")
    for where, n in sorted(report["compile_spans"].items(), key=lambda kv: -kv[1]):
        out.append(f"  {n:4d} in {where}")
    return "\n".join(out)


def load_postmortem(path):
    """Parse a flight-recorder bundle (JSONL): returns
    ``(header, metrics_snapshot, diagnostics, events)``.  Raises
    ``ValueError`` when the file is not a postmortem bundle."""
    header = None
    snapshot = None
    diagnostics = None
    events = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError(f"line {lineno} is not a JSON object")
            kind = rec.get("kind")
            if lineno == 1:
                if kind != "postmortem":
                    raise ValueError(
                        "first record is not a postmortem header "
                        f"(kind={kind!r}) — is this a flight-recorder "
                        "bundle?")
                header = rec
            elif kind == "metrics":
                snapshot = rec.get("snapshot")
            elif kind == "diagnostics":
                diagnostics = rec
            else:
                events.append(rec)
    if header is None:
        raise ValueError("empty file")
    return header, snapshot, diagnostics, events


def render_postmortem(header, snapshot, diagnostics, events, top=10):
    out = [f"postmortem: {header.get('reason', '?')}",
           f"  dumped at unix {header.get('ts')}; "
           f"{len(events)} ring events"]
    ctx = header.get("context") or {}
    for k in sorted(ctx):
        out.append(f"  context.{k} = {ctx[k]}")
    if diagnostics is not None:
        out.append("last diagnostics:")
        for k in sorted(diagnostics):
            if k not in ("kind", "ts"):
                out.append(f"  {k} = {diagnostics[k]}")
    if snapshot:
        out.append(f"metrics snapshot ({len(snapshot)} series):")
        for k in sorted(snapshot):
            out.append(f"  {k} = {snapshot[k]}")
    if events:
        out.append(f"ring (oldest first, last {min(len(events), top)} shown):")
        for rec in events[-top:]:
            kind = rec.get("kind", "?")
            name = rec.get("name") or rec.get("reason") or ""
            extra = {k: v for k, v in rec.items()
                     if k not in ("kind", "name", "ts")}
            out.append(f"  [{rec.get('ts', 0):>12.6f}] {kind:11s} {name} "
                       f"{extra if extra else ''}".rstrip())
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="Chrome trace JSON (Tracer.export_chrome), "
                                  "tracer JSONL file, or (with --postmortem) "
                                  "a flight-recorder bundle")
    ap.add_argument("--top", type=int, default=10,
                    help="entries in the self-time ranking (or postmortem "
                         "ring events shown)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON document")
    ap.add_argument("--postmortem", action="store_true",
                    help="render a flight-recorder postmortem bundle "
                         "instead of a span summary")
    args = ap.parse_args(argv)

    try:
        if args.postmortem:
            header, snapshot, diagnostics, events = load_postmortem(args.trace)
        else:
            spans, instants = load_events(args.trace)
    except OSError as e:
        print(f"trace_report: cannot read {args.trace}: "
              f"{e.strerror or e}", file=sys.stderr)
        return 2
    except (json.JSONDecodeError, UnicodeDecodeError, KeyError, ValueError,
            TypeError) as e:
        # corrupt/truncated JSON, a non-trace file, a malformed record:
        # one clear line, no traceback
        print(f"trace_report: {args.trace} is not a readable "
              f"{'postmortem bundle' if args.postmortem else 'trace file'}: "
              f"{e}", file=sys.stderr)
        return 2

    if args.postmortem:
        if args.json:
            print(json.dumps({"header": header, "metrics": snapshot,
                              "diagnostics": diagnostics, "events": events}))
        else:
            print(render_postmortem(header, snapshot, diagnostics, events,
                                    top=args.top))
        return 0
    if not spans and not instants:
        print(f"trace_report: no trace events in {args.trace}",
              file=sys.stderr)
        return 1
    report = summarize(spans, instants, top=args.top)
    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
