"""Reproduce the Sinkhorn-W2 cost table of docs/notes.md.

Measures the scanned W2 trajectory (``DistSampler.run_steps`` with the
carried-snapshot Sinkhorn term) at a given particle count, comparing the
fixed-iteration-count loop against the adaptive ``sinkhorn_tol`` exit —
the configuration pair behind the "438 → 186 → 74.5 ms/step" history in
the notes (the absolute numbers shift with the shared pool's state; the
ratios are the point).  Incumbent (fixed-count) timed first, so the
adaptive challenger must beat the pool's idle-credit bias
(docs/notes.md timing protocol).

Usage: ``python tools/w2_bench.py [--n 10000] [--iters-per-dispatch 50]``.

``--fidelity`` instead quantifies the **budgeted** large-n W2 mode's
trajectory fidelity (round-4 VERDICT item 3: "9.1 s/step at 1M" with
``sinkhorn_iters=8`` as a per-step budget is an *inexact* JKO proximal step
— the number needed a deviation band next to it).  Two samplers start from
the same init: the budget config (``--budget-iters``, default 8, the 1M
protocol) and a high-budget reference (``--ref-iters``, default 200, with
the tol exit → converged solves).  Both step together, one step per
dispatch, and the per-step max particle deviation is printed plus a summary
band.  The carried duals make the budgeted solve *resumable*: it converges
incrementally across steps while particles barely move, so the deviation
should plateau near the solver-tol band rather than compound —
``--fidelity`` is the measurement of exactly that claim.  ``--exchange
partitions`` runs the 1M spot-check pairing; the default ``all_particles``
covers the 100k ladder.
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np
import jax
import jax.numpy as jnp

import dist_svgd_tpu as dt
from dist_svgd_tpu.models.logreg import logreg_logp
from dist_svgd_tpu.utils.datasets import load_benchmark
from dist_svgd_tpu.utils.rng import init_particles_per_shard


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=10_000)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--iters-per-dispatch", type=int, default=50)
    ap.add_argument("--sinkhorn-iters", type=int, default=200)
    ap.add_argument("--samples", type=int, default=3)
    ap.add_argument("--no-fixed", action="store_true",
                    help="skip the fixed-200-iteration round-1 reference "
                         "variant (at streaming sizes, e.g. --n 100000, it "
                         "costs minutes per dispatch and the cold-tol vs "
                         "warm comparison is the point)")
    ap.add_argument("--fidelity", action="store_true",
                    help="measure the budgeted-solver trajectory deviation "
                         "instead of timing (module docstring)")
    ap.add_argument("--fidelity-steps", type=int, default=20)
    ap.add_argument("--budget-iters", type=int, default=8,
                    help="per-step Sinkhorn budget under test (the 1M "
                         "row's protocol)")
    ap.add_argument("--ref-iters", type=int, default=200,
                    help="reference solve cap (tol exit active, so this is "
                         "'converged')")
    ap.add_argument("--stepsize", type=float, default=3e-4,
                    help="SVGD stepsize for --fidelity (default: the "
                         "round-4 large-n protocol's 3e-4)")
    ap.add_argument("--exchange", default="all_particles",
                    choices=["all_particles", "partitions"],
                    help="--fidelity exchange mode (partitions = the 1M "
                         "spot-check pairing)")
    args = ap.parse_args()

    print("devices:", jax.devices(), flush=True)
    fold = load_benchmark("banana", 42)
    data = (jnp.asarray(fold.x_train), jnp.asarray(fold.t_train.reshape(-1)))
    d = 1 + fold.x_train.shape[1]
    K = args.iters_per_dispatch

    if args.fidelity:
        def build_sampler(iters):
            parts = init_particles_per_shard(0, args.n, d, args.shards)
            return dt.DistSampler(
                args.shards, logreg_logp, None, parts, data=data,
                exchange_particles=(args.exchange != "partitions"),
                exchange_scores=False,
                include_wasserstein=True, wasserstein_solver="sinkhorn",
                sinkhorn_iters=iters, sinkhorn_tol=1e-2,
                sinkhorn_warm_start=True,
            )

        budget = build_sampler(args.budget_iters)
        ref = build_sampler(args.ref_iters)
        print(
            f"fidelity: n={args.n} {args.exchange} "
            f"(pairing {budget._w2_pairing}), budget {args.budget_iters} vs "
            f"ref {args.ref_iters} iters, stepsize {args.stepsize}, h=10, "
            f"{args.fidelity_steps} steps", flush=True,
        )
        max_dev = max_rel = 0.0
        for k in range(1, args.fidelity_steps + 1):
            pb = np.asarray(budget.run_steps(1, args.stepsize, h=10.0))
            pr = np.asarray(ref.run_steps(1, args.stepsize, h=10.0))
            dev = float(np.max(np.abs(pb - pr)))
            scale = float(np.max(np.abs(pr)))
            max_dev = max(max_dev, dev)
            max_rel = max(max_rel, dev / scale)
            print(f"  step {k:3d}: max|Δx| {dev:.3e} "
                  f"(rel {dev/scale:.3e})", flush=True)
        print(
            f"fidelity band over {args.fidelity_steps} steps: "
            f"max deviation {max_dev:.3e} (relative {max_rel:.3e}); a band "
            "near the solver tol means the budgeted solve is converging "
            "across steps via the carried duals (inexact-JKO argument, "
            "docs/theory.md §4), not drifting", flush=True,
        )
        return

    def bench(tol, warm, label):
        parts = init_particles_per_shard(0, args.n, d, args.shards)
        s = dt.DistSampler(
            args.shards, logreg_logp, None, parts, data=data,
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=True, wasserstein_solver="sinkhorn",
            sinkhorn_iters=args.sinkhorn_iters, sinkhorn_tol=tol,
            sinkhorn_warm_start=warm,
        )
        out = s.run_steps(K, 3e-3, h=10.0)
        np.asarray(out)[0, 0]  # compile + fence, untimed
        best = float("inf")
        for _ in range(args.samples):
            t0 = time.perf_counter()
            out = s.run_steps(K, 3e-3, h=10.0)  # state-chained
            np.asarray(out)[0, 0]
            best = min(best, (time.perf_counter() - t0) / K)
        print(f"{label:52s} {best*1e3:8.2f} ms/step", flush=True)
        return best, np.asarray(s.particles)

    if not args.no_fixed:
        t_fixed, traj_fixed = bench(
            None, False, f"W2 fixed {args.sinkhorn_iters} iters, cold (round-1 ref)"
        )
    t_tol, traj_tol = bench(1e-2, False, "W2 tol=1e-2, cold start (round-2 incumbent)")
    t_warm, traj_warm = bench(1e-2, True, "W2 tol=1e-2 + warm-started duals (default)")
    if args.no_fixed:
        print(f"warm vs cold-tol: {t_tol/t_warm:.2f}x", flush=True)
        print(f"max final-particle deviation warm vs cold-tol: "
              f"{np.max(np.abs(traj_tol - traj_warm)):.2e}", flush=True)
        return
    print(f"tol vs fixed: {t_fixed/t_tol:.2f}x; warm vs cold-tol: "
          f"{t_tol/t_warm:.2f}x; total {t_fixed/t_warm:.2f}x", flush=True)
    print(f"max final-particle deviation vs fixed-{args.sinkhorn_iters}: "
          f"cold-tol {np.max(np.abs(traj_fixed - traj_tol)):.2e}, "
          f"warm {np.max(np.abs(traj_fixed - traj_warm)):.2e}", flush=True)


if __name__ == "__main__":
    main()
