"""Reproduce the Sinkhorn-W2 cost table of docs/notes.md.

Measures the scanned W2 trajectory (``DistSampler.run_steps`` with the
carried-snapshot Sinkhorn term) at a given particle count, comparing the
fixed-iteration-count loop against the adaptive ``sinkhorn_tol`` exit —
the configuration pair behind the "438 → 186 → 74.5 ms/step" history in
the notes (the absolute numbers shift with the shared pool's state; the
ratios are the point).  Incumbent (fixed-count) timed first, so the
adaptive challenger must beat the pool's idle-credit bias
(docs/notes.md timing protocol).

Usage: ``python tools/w2_bench.py [--n 10000] [--iters-per-dispatch 50]``.
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np
import jax
import jax.numpy as jnp

import dist_svgd_tpu as dt
from dist_svgd_tpu.models.logreg import logreg_logp
from dist_svgd_tpu.utils.datasets import load_benchmark
from dist_svgd_tpu.utils.rng import init_particles_per_shard


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=10_000)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--iters-per-dispatch", type=int, default=50)
    ap.add_argument("--sinkhorn-iters", type=int, default=200)
    ap.add_argument("--samples", type=int, default=3)
    ap.add_argument("--no-fixed", action="store_true",
                    help="skip the fixed-200-iteration round-1 reference "
                         "variant (at streaming sizes, e.g. --n 100000, it "
                         "costs minutes per dispatch and the cold-tol vs "
                         "warm comparison is the point)")
    args = ap.parse_args()

    print("devices:", jax.devices(), flush=True)
    fold = load_benchmark("banana", 42)
    data = (jnp.asarray(fold.x_train), jnp.asarray(fold.t_train.reshape(-1)))
    d = 1 + fold.x_train.shape[1]
    K = args.iters_per_dispatch

    def bench(tol, warm, label):
        parts = init_particles_per_shard(0, args.n, d, args.shards)
        s = dt.DistSampler(
            args.shards, logreg_logp, None, parts, data=data,
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=True, wasserstein_solver="sinkhorn",
            sinkhorn_iters=args.sinkhorn_iters, sinkhorn_tol=tol,
            sinkhorn_warm_start=warm,
        )
        out = s.run_steps(K, 3e-3, h=10.0)
        np.asarray(out)[0, 0]  # compile + fence, untimed
        best = float("inf")
        for _ in range(args.samples):
            t0 = time.perf_counter()
            out = s.run_steps(K, 3e-3, h=10.0)  # state-chained
            np.asarray(out)[0, 0]
            best = min(best, (time.perf_counter() - t0) / K)
        print(f"{label:52s} {best*1e3:8.2f} ms/step", flush=True)
        return best, np.asarray(s.particles)

    if not args.no_fixed:
        t_fixed, traj_fixed = bench(
            None, False, f"W2 fixed {args.sinkhorn_iters} iters, cold (round-1 ref)"
        )
    t_tol, traj_tol = bench(1e-2, False, "W2 tol=1e-2, cold start (round-2 incumbent)")
    t_warm, traj_warm = bench(1e-2, True, "W2 tol=1e-2 + warm-started duals (default)")
    if args.no_fixed:
        print(f"warm vs cold-tol: {t_tol/t_warm:.2f}x", flush=True)
        print(f"max final-particle deviation warm vs cold-tol: "
              f"{np.max(np.abs(traj_tol - traj_warm)):.2e}", flush=True)
        return
    print(f"tol vs fixed: {t_fixed/t_tol:.2f}x; warm vs cold-tol: "
          f"{t_tol/t_warm:.2f}x; total {t_fixed/t_warm:.2f}x", flush=True)
    print(f"max final-particle deviation vs fixed-{args.sinkhorn_iters}: "
          f"cold-tol {np.max(np.abs(traj_fixed - traj_tol)):.2e}, "
          f"warm {np.max(np.abs(traj_fixed - traj_warm)):.2e}", flush=True)


if __name__ == "__main__":
    main()
