"""JL001 retrace-hazard: code shapes that re-trace a jitted program per call.

Two statically-decidable hazards are flagged:

1. **``jax.jit`` applied inside a loop body** — every iteration wraps a
   fresh callable, so nothing ever hits jit's internal cache and each call
   pays a full trace+compile.  (A jit call behind an explicit memo dict —
   the repo's ``_chunk_fn``/``_run_fn`` pattern — lives outside the loop
   and is not flagged.)

2. **Python numeric literals that vary across call sites of one jitted
   callable.**  A traced (non-static) Python scalar argument is baked into
   the jaxpr as a weak-typed constant: every *distinct* value is a fresh
   trace.  The rule collects call sites of names known to be jit-wrapped in
   the same module (``@jax.jit`` / ``@partial(jax.jit, ...)`` decorated
   defs, ``name = jax.jit(...)`` and ``self.attr = jax.jit(...)``
   bindings) and flags any positional slot fed ≥ 2 distinct numeric
   literals.  Hoist the scalar into ``jnp.asarray(...)`` (traced once per
   dtype/shape) or mark the arg static.

Suppress an intentional per-value specialisation with
``# jaxlint: disable=JL001`` on the call line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.jaxlint.core import Finding, Module, _is_partial_of_tracer, last_component

RULE_ID = "JL001"
SUMMARY = "retrace hazard (jit-in-loop; Python scalar varying across call sites)"

_JIT_NAMES = {"jit"}


def _is_jit_call(node: ast.Call) -> bool:
    if last_component(node.func) in _JIT_NAMES:
        return True
    return _is_partial_of_tracer(node) and last_component(node.args[0]) in _JIT_NAMES


def _is_jit_decorator(dec: ast.AST) -> bool:
    if last_component(dec) in _JIT_NAMES:
        return True
    return isinstance(dec, ast.Call) and _is_jit_call(dec)


def _numeric_literal(node: ast.AST):
    """The float/int value of a numeric literal expression, else None."""
    if isinstance(node, ast.Constant) and type(node.value) in (int, float):
        return node.value
    if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)
            and isinstance(node.operand, ast.Constant)
            and type(node.operand.value) in (int, float)):
        return -node.operand.value
    return None


def check(module: Module) -> List[Optional[Finding]]:
    findings: List[Optional[Finding]] = []

    # ---- hazard 1: jax.jit(...) lexically inside a for/while body ----
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call) and _is_jit_call(node)):
            continue
        fn = module.enclosing_function(node)
        for anc in module.ancestors(node):
            if anc is fn:
                break
            if isinstance(anc, (ast.For, ast.While)):
                findings.append(module.finding(
                    node, RULE_ID,
                    "jax.jit called inside a loop body: each iteration wraps "
                    "a fresh callable and re-traces — hoist the jit (or a "
                    "keyed cache of it) out of the loop",
                ))
                break

    # ---- hazard 2: literal divergence across call sites ----
    jitted: Set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                jitted.add(node.name)
        elif isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Call) and _is_jit_call(node.value):
                for tgt in node.targets:
                    name = last_component(tgt)
                    if name:
                        jitted.add(name)

    # name -> arg position -> {literal value: first flagging node}
    seen: Dict[str, Dict[int, Dict[object, ast.AST]]] = {}
    calls_in_order = [n for n in ast.walk(module.tree) if isinstance(n, ast.Call)]
    calls_in_order.sort(key=lambda n: (n.lineno, n.col_offset))
    for node in calls_in_order:
        name = last_component(node.func)
        if name not in jitted:
            continue
        for pos, arg in enumerate(node.args):
            value = _numeric_literal(arg)
            if value is None:
                continue
            slot = seen.setdefault(name, {}).setdefault(pos, {})
            if value not in slot:
                slot[value] = arg
                if len(slot) == 2:
                    findings.append(module.finding(
                        arg, RULE_ID,
                        f"jitted callable '{name}' receives a second distinct "
                        f"Python scalar ({value!r}) at positional arg {pos}: "
                        "each distinct value re-traces — pass it as a device "
                        "array (jnp.asarray) or mark the arg static",
                    ))
                elif len(slot) > 2:
                    findings.append(module.finding(
                        arg, RULE_ID,
                        f"jitted callable '{name}' re-traces again at "
                        f"positional arg {pos} (literal {value!r})",
                    ))
    return findings
