"""jaxlint allowlist: accepted findings OUTSIDE the package tree.

Each entry: ``(path_suffix, rule, line_or_None, reason)``.  A finding is
allowlisted when its path ends with ``path_suffix``, its rule matches, and
(when a line is given) its line matches exactly.

Policy (ISSUE 4): allowlist entries are permitted **only** for ``tools/``
and ``experiments/`` — package code (``dist_svgd_tpu/``) must be clean or
carry a reviewed per-line ``# jaxlint: disable=`` comment at the site,
where the justification lives next to the code it excuses.  The CLI
*enforces* this: an entry whose suffix points into ``dist_svgd_tpu/``
is itself an error.

Prefer per-line disables over entries here: an entry silently survives the
code moving lines, a disable comment moves with it.  Line-pinned entries
exist for generated or vendored files one cannot annotate.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

# (path_suffix, rule, line_or_None, reason)
ALLOWLIST: List[Tuple[str, str, Optional[int], str]] = [
    # (empty at ship time: every finding in tools/ and experiments/ was
    # fixed instead — see docs/notes.md round 9.  Keep the mechanism.)
]


def is_allowlisted(path: str, rule: str, line: int,
                   allowlist: Iterable[Tuple[str, str, Optional[int], str]] = None) -> bool:
    entries = ALLOWLIST if allowlist is None else allowlist
    norm = path.replace("\\", "/")
    for suffix, arule, aline, _reason in entries:
        if arule == rule and norm.endswith(suffix):
            if aline is None or aline == line:
                return True
    return False


def validate(allowlist=None) -> List[str]:
    """Policy errors in the allowlist itself (package-tree entries)."""
    entries = ALLOWLIST if allowlist is None else allowlist
    errors = []
    for suffix, rule, _line, reason in entries:
        if "dist_svgd_tpu/" in suffix.replace("\\", "/"):
            errors.append(
                f"allowlist entry ({suffix!r}, {rule}) targets package code: "
                "fix it or use a per-line disable comment instead"
            )
        if not reason.strip():
            errors.append(f"allowlist entry ({suffix!r}, {rule}) has no reason")
    return errors


def stale_entries(findings, allowlist=None
                  ) -> List[Tuple[str, str, Optional[int], str]]:
    """Entries that waive **nothing** in ``findings`` (the full-tree lint
    result) — dead weight that silently survives the code it excused being
    fixed, moved, or deleted.  A stale entry is worse than a missing one:
    the next finding that happens to land on the same ``(suffix, rule)``
    gets waived by an excuse written for different code.  Reported by the
    CLI on full-tree runs and enforced to be empty by
    ``tests/test_jaxlint.py``.
    """
    entries = ALLOWLIST if allowlist is None else allowlist
    stale = []
    for entry in entries:
        suffix, rule, line, _reason = entry
        hit = any(
            f.rule == rule
            and f.path.replace("\\", "/").endswith(suffix)
            and (line is None or line == f.line)
            for f in findings
        )
        if not hit:
            stale.append(entry)
    return stale
