"""jaxlint core: parsing, scope analysis, the escape hatch, and the runner.

Design notes (shared by every rule module):

- **AST, not regex.**  Each rule gets a :class:`Module` — parsed tree with
  parent links, source lines, and the per-line disable set — and returns
  :class:`Finding` objects.  A rule never raises on weird-but-valid Python;
  anything it cannot resolve statically it stays silent about (precision
  over recall: this gate runs in tier-1 with a zero-finding baseline, so a
  speculative finding is a build breakage).
- **Escape hatch.**  ``# jaxlint: disable=JL003`` (comma-separated for
  several rules) on the finding's line suppresses exactly the named
  rule(s) there — the reviewable, greppable way to bless an intentional
  violation.  There is no file-level or wildcard disable by design.
- **Traced scopes.**  JL003/JL005 only fire *inside code that JAX traces*:
  functions decorated with / passed to ``jit``/``vmap``/``pmap``/``grad``/
  ``shard_map``/``lax.scan``-family wrappers, plus everything lexically
  nested in one.  Host-side driver code (chunk fetches, checkpoint I/O)
  legitimately syncs and is out of scope.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Set

DISABLE_RE = re.compile(r"#\s*jaxlint:\s*disable=([A-Za-z0-9_,\s]+)")

#: call / decorator names (last dotted component) that stage their function
#: argument through a JAX trace — the roots of "traced scope".
TRACING_WRAPPERS = {
    "jit", "pmap", "vmap", "grad", "value_and_grad", "checkpoint", "remat",
    "shard_map", "scan", "while_loop", "fori_loop", "cond", "switch",
    "associative_scan", "custom_jvp", "custom_vjp", "named_call",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    path: str
    line: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_component(node: ast.AST) -> Optional[str]:
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] if name else None


def _is_partial_of_tracer(call: ast.Call) -> bool:
    """``partial(jax.jit, ...)`` / ``functools.partial(jit, ...)``."""
    if last_component(call.func) != "partial" or not call.args:
        return False
    first = call.args[0]
    return last_component(first) in TRACING_WRAPPERS


class Module:
    """One parsed source file plus the derived facts rules share."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self.disabled: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = DISABLE_RE.search(line)
            if m:
                self.disabled[i] = {
                    r.strip().upper() for r in m.group(1).split(",") if r.strip()
                }
        # parent links (ast has none natively)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._jaxlint_parent = node  # type: ignore[attr-defined]
        self._traced: Optional[Set[ast.AST]] = None

    # -------------------------------------------------------------- #

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_jaxlint_parent", None)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return anc
        return None

    def finding(self, node: ast.AST, rule: str, message: str) -> Optional[Finding]:
        """Build a finding unless the escape hatch suppresses it."""
        line = getattr(node, "lineno", 1)
        if rule.upper() in self.disabled.get(line, set()):
            return None
        return Finding(self.path, line, rule, message)

    # -------------------------------------------------------------- #
    # traced-scope analysis (JL003 / JL005)

    def traced_functions(self) -> Set[ast.AST]:
        """FunctionDef/Lambda nodes whose bodies JAX traces (directly or by
        lexical nesting inside a traced one)."""
        if self._traced is not None:
            return self._traced
        roots: Set[ast.AST] = set()
        # name -> every FunctionDef with that name (for fn-passed-by-name)
        defs_by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)

        def mark_callable_arg(arg: ast.AST) -> None:
            if isinstance(arg, ast.Lambda):
                roots.add(arg)
            elif isinstance(arg, ast.Name):
                roots.update(defs_by_name.get(arg.id, ()))
            # nested Call args (e.g. jax.jit(jax.vmap(f))) are visited on
            # their own walk pass below

        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if last_component(dec) in TRACING_WRAPPERS:
                        roots.add(node)
                    elif isinstance(dec, ast.Call) and (
                        last_component(dec.func) in TRACING_WRAPPERS
                        or _is_partial_of_tracer(dec)
                    ):
                        roots.add(node)
            elif isinstance(node, ast.Call):
                if last_component(node.func) in TRACING_WRAPPERS:
                    for arg in node.args:
                        mark_callable_arg(arg)
                elif _is_partial_of_tracer(node):
                    for arg in node.args[1:]:
                        mark_callable_arg(arg)

        # propagate to lexically nested functions
        traced: Set[ast.AST] = set()
        for fn in roots:
            traced.add(fn)
            for inner in ast.walk(fn):
                if inner is not fn and isinstance(
                    inner, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    traced.add(inner)
        self._traced = traced
        return traced

    def in_traced_scope(self, node: ast.AST) -> bool:
        fn = self.enclosing_function(node)
        return fn is not None and fn in self.traced_functions()


def jit_static_params(fn: ast.AST) -> Set[str]:
    """Parameter names a jit decorator marks static (``static_argnames`` /
    ``static_argnums``) — trace-time Python values, exempt from the
    host-sync rule by construction."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return set()
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    names: Set[str] = set()
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        if not (last_component(dec.func) in TRACING_WRAPPERS
                or _is_partial_of_tracer(dec)):
            continue
        for kw in dec.keywords:
            values = []
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                values = [e.value for e in kw.value.elts
                          if isinstance(e, ast.Constant)]
            elif isinstance(kw.value, ast.Constant):
                values = [kw.value.value]
            if kw.arg == "static_argnames":
                names.update(v for v in values if isinstance(v, str))
            elif kw.arg == "static_argnums":
                for v in values:
                    if isinstance(v, int) and 0 <= v < len(params):
                        names.add(params[v])
    return names


# ------------------------------------------------------------------ #
# runner

SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules"}


def iter_python_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                out.append(path)
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in SKIP_DIRS and not d.startswith("."))
            for name in sorted(files):
                if name.endswith(".py"):
                    out.append(os.path.join(root, name))
    return out


def load_rules():
    """The rule registry, in rule-ID order."""
    from tools.jaxlint import (
        rules_hostsync,
        rules_lock,
        rules_retrace,
        rules_rng,
        rules_statedict,
        rules_tracer,
    )

    return [rules_retrace, rules_rng, rules_hostsync, rules_lock,
            rules_tracer, rules_statedict]


def lint_source(path: str, source: str, rules=None) -> List[Finding]:
    """Lint one in-memory source blob (the fixture-test entry point)."""
    rules = rules if rules is not None else load_rules()
    try:
        module = Module(path, source)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 1, "JL000",
                        f"syntax error: {e.msg}")]
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(f for f in rule.check(module) if f is not None)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_paths(paths: Iterable[str], rules=None) -> List[Finding]:
    rules = rules if rules is not None else load_rules()
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            findings.extend(lint_source(path, fh.read(), rules))
    return findings
