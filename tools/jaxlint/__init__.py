"""jaxlint: repo-native JAX/TPU discipline analyzer + runtime retrace sentry.

Static rules (AST-based, fixture-tested, tier-1-enforced with a
zero-finding baseline for package code — see tools/README.md for the full
table and tests/test_jaxlint.py for the gate):

- **JL001 retrace-hazard** — jit-in-loop; Python scalars varying across a
  jitted callable's call sites (``rules_retrace``).
- **JL002 key-reuse** — a PRNG key consumed twice without split/fold_in;
  ad-hoc ``PRNGKey`` construction outside ``utils/rng.py`` (``rules_rng``).
- **JL003 host-sync-in-hot-path** — float()/.item()/np.asarray/... inside
  traced code (``rules_hostsync``).
- **JL004 lock-discipline** — ``self._x`` assigned both inside and outside
  ``with self._lock`` (``rules_lock``).
- **JL005 tracer-leak** — Python side effects under jit/scan
  (``rules_tracer``).
- **JL006 state-dict-drift** — attributes mutated alongside persisted
  state in a checkpointed class (defines ``state_dict`` +
  ``load_state_dict``) but absent from both protocol methods — silent
  kill→resume field loss (``rules_statedict``).

Escape hatch: ``# jaxlint: disable=JL00N`` on the offending line.
Runtime half: :func:`retrace_sentry` counts XLA compiles inside a region
(zero-compile steady-state contract — wired into serve_bench/perf_regress).

Program-level sibling family — **XP001–XP005** — lives in
``dist_svgd_tpu/analysis/audit.py`` and shares this package's ``Finding``
+ allowlist machinery, but audits *compiled plans* (jaxpr + lowered
StableHLO) instead of source text: XP001 materialized-nxn (Gram matrix in
a gram-free-declared program), XP002 collective-in-unsharded-plan, XP003
donation-dropped, XP004 f64-promotion, XP005 bf16-pollution.  There is no
source line to hang a disable comment on; the allowlist (path suffix
``plan://<label>``) is the blessing mechanism, and
``tools/program_audit.py`` is the gate.  Reporting (text/json/github) is
shared through ``tools/jaxlint/report.py``.
"""

from tools.jaxlint.core import Finding, lint_paths, lint_source, load_rules
from tools.jaxlint.sentry import assert_no_recompiles, retrace_sentry

__all__ = [
    "Finding",
    "lint_paths",
    "lint_source",
    "load_rules",
    "retrace_sentry",
    "assert_no_recompiles",
]
