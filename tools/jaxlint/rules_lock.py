"""JL004 lock-discipline: attributes mutated both with and without the lock.

Scope: the threaded modules (``serving/``, ``utils/metrics.py``,
``distsampler.py`` and anything else handed to the analyzer) — any class
that owns a lock (``self._lock = threading.Lock()`` / ``RLock`` /
``Condition`` / ``Semaphore`` in any method) gets its instance-attribute
stores partitioned into lock-guarded and bare.  An attribute assigned
*both* inside a ``with self._lock:`` block somewhere *and* outside one
elsewhere is flagged at each unguarded site: half-guarded state is the
worst of both worlds — the guarded sites document an invariant the bare
sites silently break (torn multi-field updates, lost increments).

``__init__`` is exempt (construction precedes sharing), as are attributes
only ever written without the lock (possibly single-threaded by design —
that contract is the class author's to state, not this rule's to guess).
Suppress a deliberate bare write (e.g. a stop flag that tolerates racing)
with ``# jaxlint: disable=JL004``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.jaxlint.core import Finding, Module, last_component

RULE_ID = "JL004"
SUMMARY = "attribute assigned both inside and outside `with self._lock`"

_LOCK_TYPES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


def _self_attr_target(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attribute names bound to a threading lock anywhere in the class."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if last_component(node.value.func) in _LOCK_TYPES:
                for tgt in node.targets:
                    attr = _self_attr_target(tgt)
                    if attr:
                        out.add(attr)
    return out


def _under_lock(module: Module, node: ast.AST, lock_attrs: Set[str],
                stop: ast.AST) -> bool:
    for anc in module.ancestors(node):
        if anc is stop:
            break
        if isinstance(anc, ast.With):
            for item in anc.items:
                expr = item.context_expr
                # `with self._lock:` or `with self._lock.acquire_timeout(..)`
                attr = _self_attr_target(expr)
                if attr is None and isinstance(expr, ast.Call):
                    base = expr.func
                    if isinstance(base, ast.Attribute):
                        attr = _self_attr_target(base.value)
                if attr in lock_attrs:
                    return True
    return False


def check(module: Module) -> List[Optional[Finding]]:
    findings: List[Optional[Finding]] = []
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        lock_attrs = _lock_attrs(cls)
        if not lock_attrs:
            continue
        guarded: Set[str] = set()
        bare: Dict[str, List[ast.AST]] = {}
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            init = method.name == "__init__"
            for node in ast.walk(method):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for tgt in targets:
                    attr = _self_attr_target(tgt)
                    if attr is None or attr in lock_attrs:
                        continue
                    if _under_lock(module, node, lock_attrs, method):
                        guarded.add(attr)
                    elif not init:
                        bare.setdefault(attr, []).append(node)
        for attr in sorted(guarded & set(bare)):
            for node in bare[attr]:
                findings.append(module.finding(
                    node, RULE_ID,
                    f"'self.{attr}' is assigned under the lock elsewhere in "
                    f"{cls.name} but bare here: a concurrent reader/writer "
                    "can observe a torn update — take the lock (or disable "
                    "with a one-line justification if single-threaded by "
                    "contract)",
                ))
    return findings
