"""jaxlint CLI: ``python -m tools.jaxlint [paths...] [--format=...]``.

Default paths are the three enforced trees (``dist_svgd_tpu``, ``tools``,
``experiments``) resolved against the repo root, so the bare invocation
from anywhere inside the repo reproduces exactly what the tier-1 gate
(``tests/test_jaxlint.py``) enforces.  Exit code 0 = no non-allowlisted
findings; 1 = findings; 2 = the allowlist itself violates policy (a
package-tree entry, a missing reason, or — on full-tree runs — a stale
entry that waives nothing).

Output rides ``tools/jaxlint/report.py`` (the renderer shared with
``tools/program_audit.py``): ``--format=text`` (default, clickable
``path:line`` lines), ``--format=json`` (one machine document), or
``--format=github`` (workflow-command annotations CI surfaces inline on
the PR).  ``--json`` remains as an alias for ``--format=json``.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from tools.jaxlint import allowlist as allowlist_mod
from tools.jaxlint import report
from tools.jaxlint.core import Finding, lint_paths, load_rules

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_PATHS = ("dist_svgd_tpu", "tools", "experiments")


def rule_table() -> List[dict]:
    return [{"rule": r.RULE_ID, "summary": r.SUMMARY} for r in load_rules()]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.jaxlint",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: {' '.join(DEFAULT_PATHS)} "
                         "under the repo root)")
    ap.add_argument("--format", choices=report.FORMATS, default="text",
                    dest="fmt", help="output format (default: text)")
    ap.add_argument("--json", action="store_const", const="json",
                    dest="fmt", help="alias for --format=json")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--no-allowlist", action="store_true",
                    help="report allowlisted findings too (audit mode)")
    args = ap.parse_args(argv)

    if args.list_rules:
        if args.fmt == "json":
            import json as _json

            print(_json.dumps({"rules": rule_table()}, indent=2))
        else:
            for row in rule_table():
                print(f"{row['rule']}  {row['summary']}")
        return 0

    errors = allowlist_mod.validate()
    if errors:
        for e in errors:
            print(f"jaxlint: allowlist policy error: {e}", file=sys.stderr)
        return 2

    full_tree = not args.paths
    paths = args.paths or [os.path.join(REPO_ROOT, p) for p in DEFAULT_PATHS]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"jaxlint: no such path(s): {missing}", file=sys.stderr)
        return 2

    findings = lint_paths(paths)
    kept: List[Finding] = []
    waived: List[Finding] = []
    for f in findings:
        if not args.no_allowlist and allowlist_mod.is_allowlisted(
                f.path, f.rule, f.line):
            waived.append(f)
        else:
            kept.append(f)

    # stale-entry policy only judges the FULL enforced tree: a subset run
    # legitimately misses the findings other trees' entries waive
    stale = allowlist_mod.stale_entries(findings) if full_tree else []

    report.render(kept, args.fmt, rules=rule_table(), paths=paths,
                  allowlisted=[f.as_dict() for f in waived],
                  stale_allowlist=[list(e) for e in stale])
    if args.fmt == "text":
        summary = (f"jaxlint: {len(kept)} finding(s)"
                   + (f", {len(waived)} allowlisted" if waived else ""))
        print(summary, file=sys.stderr if kept else sys.stdout)
    if stale:
        for suffix, rule, line, _reason in stale:
            print(
                f"jaxlint: stale allowlist entry ({suffix!r}, {rule}, "
                f"{line}): matches no current finding — delete it",
                file=sys.stderr,
            )
        return 2
    return 1 if kept else 0


if __name__ == "__main__":
    sys.exit(main())
