"""JL005 tracer-leak: Python side effects inside traced code.

Inside a ``jit``/``scan``-traced function, Python-level mutation runs ONCE
at trace time with abstract tracers — not per step at runtime.  The two
failure shapes:

- **leaks**: storing a value on ``self`` or a module global from inside
  the trace captures a tracer that outlives its trace (the classic
  ``UnexpectedTracerError``, or worse: a stale concrete value silently
  reused by later calls);
- **dead side effects**: appending to a closure list, writing a
  ``global``/``nonlocal``, calling ``print`` — all execute at trace time
  only, so the steady-state program does nothing and the author's
  accounting is fiction.

Flagged inside traced scopes: assignments to ``self.*`` / class attributes,
``global``/``nonlocal`` declarations, ``print(...)`` calls, and
``.append``/``.extend``/``.add``/``.update`` calls on names not bound in
the traced function itself (closure mutation).  ``jax.debug.print`` /
``jax.debug.callback`` are the sanctioned effect path and are not flagged.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.jaxlint.core import Finding, Module, dotted_name

RULE_ID = "JL005"
SUMMARY = "tracer leak / Python side effect under jit or scan"

_MUTATORS = {"append", "extend", "add", "update", "insert", "setdefault"}


def _local_names(fn: ast.AST) -> Set[str]:
    """Names bound inside ``fn`` (params, assignments, loop targets,
    comprehension targets) — excluding nested function bodies."""
    names: Set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        args = fn.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            names.add(a.arg)
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.add(node.name)
            continue
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        stack.extend(ast.iter_child_nodes(node))
    return names


def check(module: Module) -> List[Optional[Finding]]:
    findings: List[Optional[Finding]] = []
    traced = module.traced_functions()
    for fn in traced:
        locals_here = None  # computed lazily per traced fn
        for node in ast.walk(fn):
            # analyse each traced fn's own statements once: nested traced
            # fns are iterated separately, so skip nodes whose nearest
            # enclosing function is not `fn`
            if node is fn or module.enclosing_function(node) is not fn:
                continue
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                kind = "global" if isinstance(node, ast.Global) else "nonlocal"
                findings.append(module.finding(
                    node, RULE_ID,
                    f"'{kind}' write inside traced code runs once at trace "
                    "time, not per step — thread the value through the "
                    "carry/return instead",
                ))
                continue
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for tgt in targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id in ("self", "cls")):
                    findings.append(module.finding(
                        node, RULE_ID,
                        f"assignment to {tgt.value.id}.{tgt.attr} inside "
                        "traced code stores a tracer on the instance (leak) "
                        "— return the value from the traced function and "
                        "assign on the host side",
                    ))
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name == "print":
                    findings.append(module.finding(
                        node, RULE_ID,
                        "print() under jit fires once at trace time with "
                        "tracers — use jax.debug.print for runtime values",
                    ))
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in _MUTATORS
                      and isinstance(node.func.value, ast.Name)):
                    if locals_here is None:
                        locals_here = _local_names(fn)
                    base = node.func.value.id
                    if base not in locals_here and base not in ("self", "cls"):
                        findings.append(module.finding(
                            node, RULE_ID,
                            f"'{base}.{node.func.attr}(...)' mutates a "
                            "closure object inside traced code: the mutation "
                            "happens at trace time only (and may capture a "
                            "tracer) — accumulate through the scan carry or "
                            "return value",
                        ))
    return findings
