"""One reporting path for findings: text, JSON, and GitHub annotations.

Both the jaxlint CLI and ``tools/program_audit.py`` emit
:class:`~tools.jaxlint.core.Finding` lists; this module is the single
place that turns them into output so CI consumes one format family
regardless of which gate produced the finding:

- ``text`` — the clickable ``path:line: RULE message`` lines.
- ``json`` — one document: ``{"findings": [...], ...extra}``.
- ``github`` — workflow commands (``::error file=...,line=...,
  title=RULE::message``) that GitHub renders as inline PR annotations.
  Newlines/percents in messages are %-escaped per the workflow-command
  spec; program-level findings (pseudo-paths like ``plan://label``) keep
  the pseudo-path in ``file=`` — GitHub shows them as repo-level
  annotations, which is the right rendering for a finding with no source
  line.
"""

from __future__ import annotations

import json
import sys
from typing import IO, Iterable, List, Optional

FORMATS = ("text", "json", "github")


def _escape_property(s: str) -> str:
    return (s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
            .replace(":", "%3A").replace(",", "%2C"))


def _escape_data(s: str) -> str:
    return s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")


def github_annotation(finding, level: str = "error") -> str:
    """One ``::error`` workflow command for a finding."""
    return (
        f"::{level} file={_escape_property(finding.path)},"
        f"line={max(finding.line, 1)},"
        f"title={_escape_property(finding.rule)}::"
        f"{_escape_data(finding.message)}"
    )


def render(findings: Iterable, fmt: str = "text",
           stream: Optional[IO[str]] = None, **extra) -> None:
    """Write ``findings`` to ``stream`` (stdout by default) in ``fmt``.

    ``extra`` keys ride the JSON document verbatim (rule tables, waived
    findings, card summaries); text/github ignore them — machine context
    belongs in the machine format.
    """
    if fmt not in FORMATS:
        raise ValueError(f"format must be one of {FORMATS}, got {fmt!r}")
    out = stream if stream is not None else sys.stdout
    findings = list(findings)
    if fmt == "json":
        doc = {"findings": [f.as_dict() for f in findings]}
        doc.update(extra)
        print(json.dumps(doc, indent=2), file=out)
    elif fmt == "github":
        for f in findings:
            print(github_annotation(f), file=out)
    else:
        for f in findings:
            print(f.format(), file=out)
