"""JL006 state-dict-drift: mutable state a checkpoint silently loses.

Scope: any class that defines **both** ``state_dict`` and
``load_state_dict`` (the repo's checkpoint protocol — samplers, streaming
sources, supervisors).  The failure this catches is the kill→resume field
loss: an attribute initialized in ``__init__`` and *re-assigned during
operation* by a method that also mutates persisted state, yet never
touched by ``state_dict``/``load_state_dict`` — after a resume the
persisted fields come back and the drifted sibling silently resets to its
construction value.

The co-mutation requirement is the precision guard (zero-finding tier-1
baseline, so speculative findings are build breakages): an attribute only
ever set in ``__init__`` is configuration (reconstructed by the
constructor, correctly absent from the checkpoint), and a method that
mutates *only* unpersisted attributes is a cache/program builder
(compiled-executable caches are rebuilt on load by design).  Only when a
method updates persisted state **and** an unpersisted ``__init__``
attribute in the same breath is that attribute evolving with the
checkpointed trajectory — exactly the field someone forgot to add to
``state_dict``.

Attributes the protocol methods touch in *any* way (read, write, or via
``getattr``/``setattr`` with a literal name) count as persisted; so do
attributes whose name appears as a string literal inside either method
(manifest keys are commonly built via dict literals).  The lazy-build
idiom (``if self._x is None: self._x = build(...)``) is exempt wherever
the store sits under such a guard — an attribute rebuilt on demand from
other state is a cache, not trajectory state.  Suppress a deliberate
transient (e.g. a stats field that must reset on resume) with
``# jaxlint: disable=JL006`` at the drifting assignment.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.jaxlint.core import Finding, Module

RULE_ID = "JL006"
SUMMARY = ("attribute mutated alongside persisted state but absent from "
           "state_dict/load_state_dict")

_PROTOCOL = ("state_dict", "load_state_dict")


def _self_attrs_assigned(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for tgt in targets:
            for el in ast.walk(tgt):  # tuple unpacking targets included
                if (isinstance(el, ast.Attribute)
                        and isinstance(el.value, ast.Name)
                        and el.value.id == "self"):
                    out.add(el.attr)
    return out


def _under_lazy_guard(module: Module, node: ast.AST, attr: str,
                      stop: ast.AST) -> bool:
    """True when ``node`` sits inside ``if self.<attr> is None:`` — the
    lazy-build cache idiom."""
    for anc in module.ancestors(node):
        if anc is stop:
            break
        if isinstance(anc, ast.If) and isinstance(anc.test, ast.Compare):
            t = anc.test
            if (isinstance(t.left, ast.Attribute)
                    and isinstance(t.left.value, ast.Name)
                    and t.left.value.id == "self" and t.left.attr == attr
                    and len(t.ops) == 1 and isinstance(t.ops[0], ast.Is)
                    and len(t.comparators) == 1
                    and isinstance(t.comparators[0], ast.Constant)
                    and t.comparators[0].value is None):
                return True
    return False


def _persisted_attrs(fn: ast.AST) -> Set[str]:
    """Every attribute a protocol method touches: direct ``self.x`` loads
    and stores, plus string literals that name an attribute (manifest-key
    dicts, ``getattr(self, "x")``)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            out.add(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
            out.add("_" + node.value)  # "particles" key ↔ _particles attr
    return out


def check(module: Module) -> List[Optional[Finding]]:
    findings: List[Optional[Finding]] = []
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods: Dict[str, ast.AST] = {
            m.name: m for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if not all(name in methods for name in _PROTOCOL):
            continue
        persisted: Set[str] = set()
        for name in _PROTOCOL:
            persisted |= _persisted_attrs(methods[name])
        init = methods.get("__init__")
        if init is None:
            continue
        init_attrs = _self_attrs_assigned(init)
        seen: Set[str] = set()
        for name, method in methods.items():
            if name in _PROTOCOL or name == "__init__":
                continue
            assigned = _self_attrs_assigned(method)
            if not (assigned & persisted):
                continue  # no co-mutation: cache/program builder
            for attr in sorted((assigned & init_attrs) - persisted - seen):
                # report once per attribute, at its first drifting store
                for node in ast.walk(method):
                    targets = []
                    if isinstance(node, ast.Assign):
                        targets = node.targets
                    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                        targets = [node.target]
                    if any(isinstance(el, ast.Attribute)
                           and isinstance(el.value, ast.Name)
                           and el.value.id == "self" and el.attr == attr
                           for tgt in targets for el in ast.walk(tgt)):
                        if _under_lazy_guard(module, node, attr, method):
                            continue
                        seen.add(attr)
                        findings.append(module.finding(
                            node, RULE_ID,
                            f"'self.{attr}' is initialized in __init__ and "
                            f"mutated here alongside persisted state, but "
                            f"{cls.name}.state_dict/load_state_dict never "
                            "touch it — a kill→resume silently resets it "
                            "(persist it, or disable with a why-transient "
                            "justification)",
                        ))
                        break
    return findings
