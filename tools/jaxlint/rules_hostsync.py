"""JL003 host-sync-in-hot-path: device→host syncs inside traced code.

The hot path of this codebase is its traced scopes — the jitted step/scan
programs ``Sampler.run`` / ``DistSampler.run_steps`` dispatch and the
jitted serve kernels behind ``PredictiveEngine`` (everything JAX traces:
``jit``/``vmap``/``grad``-wrapped functions, ``lax.scan``-family bodies,
and code lexically nested in them).  Inside a trace, a host conversion is
never what the author wanted:

- ``float()`` / ``int()`` / ``bool()`` on a traced value raises a
  ``ConcretizationTypeError`` at trace time — or, when it happens to hit a
  trace-time constant, silently bakes the value into the program so the
  callable re-traces per value;
- ``.item()`` / ``np.asarray`` / ``np.array`` / ``jax.device_get`` /
  ``.block_until_ready()`` force a device fence; reached through a jitted
  caller they are a per-step host round trip hiding inside a step function.

Driver-side host fetches (checkpoint saves, chunked-history ``np.asarray``
overlap copies) are *deliberate* syncs outside any trace and are not
flagged.  For the rare intentional trace-time constant, use
``# jaxlint: disable=JL003`` on the line.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.jaxlint.core import (
    Finding,
    Module,
    dotted_name,
    jit_static_params,
    last_component,
)

RULE_ID = "JL003"
SUMMARY = "host sync (float/item/np.asarray/...) inside traced code"

_CASTS = {"float", "int", "bool", "complex"}
_NP_FUNCS = {"asarray", "array", "copyto", "frombuffer"}
_SYNC_ATTRS = {"item", "block_until_ready", "tolist", "__array__"}
_NP_MODULES = {"np", "numpy", "onp"}


def _is_literalish(node: ast.AST) -> bool:
    """Constant-folding-safe expressions float()/int() may legally wrap at
    trace time (pure Python literals and simple arithmetic on them)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.BinOp):
        return _is_literalish(node.left) and _is_literalish(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_literalish(node.operand)
    return False


def _is_static_jit_arg(module: Module, node: ast.Call) -> bool:
    """``float(x)`` where ``x`` is a ``static_argnames`` parameter of an
    enclosing jitted function: a sanctioned trace-time cast (the Pallas
    wrappers' ``float(bandwidth)`` idiom), not a host sync."""
    arg = node.args[0]
    if not isinstance(arg, ast.Name):
        return False
    fn = module.enclosing_function(node)
    while fn is not None:
        if arg.id in jit_static_params(fn):
            return True
        fn = module.enclosing_function(fn)
    return False


def check(module: Module) -> List[Optional[Finding]]:
    findings: List[Optional[Finding]] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) or not module.in_traced_scope(node):
            continue
        func = node.func
        # float(x) / int(x) / bool(x) on non-literal args
        if (isinstance(func, ast.Name) and func.id in _CASTS
                and node.args and not _is_literalish(node.args[0])
                and not _is_static_jit_arg(module, node)):
            findings.append(module.finding(
                node, RULE_ID,
                f"{func.id}() on a value inside traced code: concretizes the "
                "tracer (error or silent per-value retrace) — keep it a "
                "device value or hoist the cast to the host driver",
            ))
            continue
        if isinstance(func, ast.Attribute):
            leaf = func.attr
            base = dotted_name(func.value)
            if leaf in _SYNC_ATTRS:
                findings.append(module.finding(
                    node, RULE_ID,
                    f".{leaf}() inside traced code forces a device→host "
                    "sync in the hot path — return the device value and "
                    "fetch it once, outside the trace",
                ))
            elif base in _NP_MODULES and leaf in _NP_FUNCS:
                findings.append(module.finding(
                    node, RULE_ID,
                    f"{base}.{leaf}() inside traced code pulls the value to "
                    "host per step — use jnp (stays on device) or move the "
                    "fetch out of the traced function",
                ))
            elif base and base.split(".")[0] == "jax" and leaf == "device_get":
                findings.append(module.finding(
                    node, RULE_ID,
                    "jax.device_get inside traced code is a per-step host "
                    "round trip — fetch outside the trace",
                ))
    return findings
