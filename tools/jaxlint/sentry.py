"""Runtime retrace sentry: count XLA compilations inside a code region.

The static rules (JL001) catch the retrace shapes that are decidable from
source; this is the runtime backstop for the rest — a context manager that
listens to jax's monitoring events and counts how many times the region
actually traced and compiled:

    with retrace_sentry() as sentry:
        serve_steady_state_traffic()
    assert sentry.compiles == 0, sentry.report()

Steady-state regions (the serve-bench timed window, perf-regress
measurement rounds) carry a **zero-compile contract**: everything was
pre-traced during warmup, so any in-window compile is a retrace bug —
a shape that escaped the padding buckets, a Python scalar baked into a
jaxpr, an eager jnp op on a novel shape.  ``tools/serve_bench.py`` and
``tools/perf_regress.py`` wire this in and FAIL on a nonzero count.

Implementation: ``jax.monitoring`` duration events (present in jax
0.4.x and 0.5.x) — ``.../backend_compile_duration`` fires once per XLA
compilation, ``.../jaxpr_trace_duration`` once per trace.  Listeners are
global in jax, so the sentry keeps its own nesting-safe registration and
counts only between ``__enter__`` and ``__exit__``; counting is
thread-safe (serve-path compiles happen on worker threads).  On a jax
without these events the sentry degrades to counting nothing and says so
(``supported = False``) rather than breaking the bench.
"""

from __future__ import annotations

import threading
from typing import List, Optional

_COMPILE_EVENT_SUBSTR = "backend_compile"
_TRACE_EVENT_SUBSTR = "jaxpr_trace"


class RetraceSentry:
    """Counter state for one ``retrace_sentry()`` region."""

    def __init__(self, label: str = ""):
        self.label = label
        self.compiles = 0
        self.traces = 0
        self.supported = True
        self._lock = threading.Lock()
        self._active = False

    def _on_event(self, name: str, *args, **kwargs) -> None:
        if not self._active:
            return
        with self._lock:
            if _COMPILE_EVENT_SUBSTR in name:
                self.compiles += 1
            elif _TRACE_EVENT_SUBSTR in name:
                self.traces += 1

    def report(self) -> dict:
        return {
            "label": self.label,
            "compiles": self.compiles,
            "traces": self.traces,
            "supported": self.supported,
        }


class retrace_sentry:
    """Context manager counting XLA compiles/traces inside the region.

    Nestable and re-entrant-safe; listener registration failures degrade
    to ``supported=False`` instead of raising (a bench must never die to
    its own instrumentation).
    """

    def __init__(self, label: str = ""):
        self._state = RetraceSentry(label)
        self._registered = False

    def __enter__(self) -> RetraceSentry:
        try:
            from jax._src import monitoring

            monitoring.register_event_duration_secs_listener(
                self._state._on_event
            )
            self._registered = True
        except Exception:
            self._state.supported = False
        self._state._active = True
        return self._state

    def __exit__(self, *exc) -> None:
        self._state._active = False
        if self._registered:
            try:
                from jax._src import monitoring

                monitoring._unregister_event_duration_listener_by_callback(
                    self._state._on_event
                )
            except Exception:
                # leaking one inert listener (guarded by _active=False)
                # beats crashing the caller's exit path
                pass
            self._registered = False


def assert_no_recompiles(fn, *args, label: str = "", **kwargs):
    """Run ``fn`` under a sentry; raise if it compiled anything.

    The one-liner for tests: first call ``fn`` once OUTSIDE this helper to
    warm its caches, then assert steady state with it."""
    with retrace_sentry(label) as sentry:
        out = fn(*args, **kwargs)
    if sentry.compiles:
        raise AssertionError(
            f"steady-state region {label or fn!r} compiled "
            f"{sentry.compiles} XLA program(s); expected 0 — {sentry.report()}"
        )
    return out
