"""JL002 key-reuse: PRNG keys consumed twice, and ad-hoc key construction.

Reused keys are the silent correctness killer in an SVGD codebase: the
stochastic minibatch streams Algorithm 1's score estimate relies on
(Liu & Wang 2016) are only unbiased if every draw consumes a *fresh* key —
a reused key correlates draws that the estimator treats as independent,
and nothing crashes.  Two checks:

1. **Double consumption.**  Within one function, a key bound to a name and
   passed bare to two ``jax.random`` sampling ops (or ``draw_minibatch``)
   without an intervening rebind (``split``/``fold_in``/fresh assignment)
   is flagged at the second use.  A bare-name key consumed *inside a loop*
   whose body never rebinds it is flagged immediately — the classic
   per-iteration reuse.

2. **Ad-hoc construction.**  ``jax.random.PRNGKey(...)`` / ``jax.random.
   key(...)`` anywhere outside ``utils/rng.py`` is flagged: the blessed
   pattern is ``dist_svgd_tpu.utils.rng.as_key(seed)`` (plus the stream
   helpers there), so seed→key policy lives in exactly one module.

Derivations (``split``/``fold_in``) are not consumption: passing
``jax.random.fold_in(key, i)`` to a sampler is the *correct* pattern and
never flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.jaxlint.core import Finding, Module, dotted_name, last_component

RULE_ID = "JL002"
SUMMARY = "PRNG key reused / constructed outside utils/rng.py"

#: jax.random ops that CONSUME the key passed as their first argument.
CONSUMERS = {
    "normal", "uniform", "choice", "bernoulli", "categorical", "permutation",
    "randint", "truncated_normal", "gumbel", "exponential", "beta", "gamma",
    "dirichlet", "laplace", "logistic", "poisson", "rademacher", "cauchy",
    "multivariate_normal", "orthogonal", "ball", "bits", "t", "shuffle",
    "draw_minibatch",
}

#: key constructors (old- and new-style) whose use outside utils/rng.py is
#: ad-hoc construction.
KEY_CONSTRUCTORS = {"PRNGKey", "key"}


def _is_random_consumer(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if name is None:
        return False
    leaf = name.rsplit(".", 1)[-1]
    if leaf == "draw_minibatch":
        return True
    return leaf in CONSUMERS and ("random" in name.split(".") or name == leaf)


def _functions(module: Module):
    yield module.tree  # module scope counts as one "function"
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            yield node


def _own_nodes(module: Module, fn) -> List[ast.AST]:
    """Nodes of ``fn`` excluding nested function bodies (each scope is
    analysed on its own), in source order."""
    out = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    out.sort(key=lambda n: (getattr(n, "lineno", 0), getattr(n, "col_offset", 0)))
    return out


def check(module: Module) -> List[Optional[Finding]]:
    findings: List[Optional[Finding]] = []

    # ---- check 2: ad-hoc construction outside utils/rng.py ----
    path = module.path.replace("\\", "/")
    if not path.endswith("utils/rng.py"):
        # names imported FROM jax.random (`from jax.random import PRNGKey`
        # / `... import key as mk`): bare calls to these are construction
        # too, not just the dotted jax.random.PRNGKey form
        from_imported: set = set()
        for node in ast.walk(module.tree):
            if (isinstance(node, ast.ImportFrom)
                    and node.module == "jax.random"):
                for alias in node.names:
                    if alias.name in KEY_CONSTRUCTORS:
                        from_imported.add(alias.asname or alias.name)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None:
                continue
            leaf = name.rsplit(".", 1)[-1]
            dotted_hit = (leaf in KEY_CONSTRUCTORS
                          and "random" in name.split("."))
            bare_hit = name in from_imported
            if dotted_hit or bare_hit:
                findings.append(module.finding(
                    node, RULE_ID,
                    f"ad-hoc jax.random key construction ({name}): build "
                    "keys through dist_svgd_tpu.utils.rng (as_key / the "
                    "stream helpers) so seed policy lives in one module",
                ))

    # ---- check 1: double consumption within a scope ----
    for fn in _functions(module):
        # (name, node, loop_node_or_None) consumption events + rebind lines
        consumptions: List[Tuple[str, ast.Call, Optional[ast.AST]]] = []
        rebinds: Dict[str, List[int]] = {}
        nodes = _own_nodes(module, fn)
        for node in nodes:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    for name_node in ast.walk(tgt):
                        if isinstance(name_node, ast.Name):
                            rebinds.setdefault(name_node.id, []).append(node.lineno)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(node.target, ast.Name):
                    rebinds.setdefault(node.target.id, []).append(node.lineno)
            elif isinstance(node, ast.For):
                for name_node in ast.walk(node.target):
                    if isinstance(name_node, ast.Name):
                        rebinds.setdefault(name_node.id, []).append(node.lineno)
            elif isinstance(node, ast.Call) and _is_random_consumer(node):
                if node.args and isinstance(node.args[0], ast.Name):
                    loop = None
                    for anc in module.ancestors(node):
                        if anc is fn:
                            break
                        if isinstance(anc, (ast.For, ast.While)):
                            loop = anc
                            break
                    consumptions.append((node.args[0].id, node, loop))

        # keys = names that are consumed at least once AND ever look like a
        # key (consumed by a jax.random op first arg is evidence enough)
        last_use_line: Dict[str, int] = {}
        for name, node, loop in consumptions:
            line = node.lineno
            if loop is not None:
                rebound_in_loop = any(
                    loop.lineno <= rl <= (loop.end_lineno or rl)
                    for rl in rebinds.get(name, ())
                )
                if not rebound_in_loop:
                    findings.append(module.finding(
                        node, RULE_ID,
                        f"key '{name}' consumed inside a loop without a "
                        "per-iteration split/fold_in: every iteration draws "
                        "the SAME stream",
                    ))
                    continue
            prev = last_use_line.get(name)
            if prev is not None:
                rebound_between = any(
                    prev < rl <= line for rl in rebinds.get(name, ())
                )
                if not rebound_between:
                    findings.append(module.finding(
                        node, RULE_ID,
                        f"key '{name}' consumed again (first use line {prev}) "
                        "without an intervening split/fold_in: the two draws "
                        "are perfectly correlated",
                    ))
            last_use_line[name] = line
    return findings
