"""Trace-driven workload replay: production-shaped traffic against the
serving stack, and the ``serve_storm`` adaptive-vs-static capacity A/B.

``tools/serve_bench.py``'s closed/open loops answer "how fast is the
request path" at a FIXED rate and request shape.  Millions of users do not
offer fixed-rate traffic: rates swing diurnally, bursts arrive in Poisson
clumps, request sizes are heavy-tailed, and tenant demand is skewed with
occasional flash crowds.  This tool generates that shape as a **fully
seeded, deterministic trace** and replays it open-loop (latency charged
from the *scheduled* arrival — no coordinated omission) against an
in-process ``MicroBatcher``+engine / ``ModelRegistry``, or a live
``serving.server`` URL:

- :class:`TraceConfig` / :func:`generate_trace` — the workload model:
  a sinusoidal diurnal envelope × scheduled burst multipliers drives a
  non-homogeneous Poisson arrival process (thinning, so the schedule is
  an exact draw, not a discretisation); request row counts follow a
  bounded power law (``p ∝ rows^-alpha``); tenant identity follows a
  Zipf-skewed mix with flash-crowd windows that shift mass onto one
  tenant.  Same seed ⇒ identical arrival schedule, sizes, and per-tenant
  mix, replay after replay (regression-pinned);
- :func:`replay` — issues the trace in real time and records one row per
  event: resolved / shed (``Overloaded`` → the 429 path) / error / lost,
  with latency measured from the scheduled arrival;
- :func:`run_storm` — the ``serve_storm`` bench row (ROADMAP item 5): a
  steady → 2×-overload burst → recovery trace, replayed **identically**
  against static batcher configurations and against the
  :class:`~dist_svgd_tpu.serving.autoscale.AutoscaleController`, under
  the retrace sentry.  The row gates in ``tools/perf_regress.py``:
  any lost non-shed request or any in-window steady-state recompile is
  an unconditional FAIL; ``storm_goodput_2x`` (the polite — non-flooding
  — tenants' completions within the latency objective, per second over
  the whole storm) and ``storm_recover_s`` (burst end → first healthy
  polite second) gate against median+MAD incumbent windows.

Why the A/B is the headline: no static configuration defends the polite
tenants through a flash crowd.  A FIFO queue admits the flood until full,
so every tenant's delay grows to the whole backlog ahead of it; a wide
static window additionally pays its coalescing floor on every steady
request.  The controller tightens quotas into admission enforcement
while overloaded — the hog is refused before it occupies queue rows the
polite tenants would wait behind — and restores them when demand
releases.  The measured claim is strictly higher polite goodput AND
strictly fewer polite p99-breach-seconds than the best static arm on the
identical trace (docs/notes.md round 18).

Usage::

    python tools/workload_replay.py --mode storm          # the bench row
    python tools/workload_replay.py --mode trace          # dump the trace
    python tools/workload_replay.py --mode replay --url http://host:8000
"""

import argparse
import json
import math
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dist_svgd_tpu.serving.batcher import _percentile  # noqa: E402


# --------------------------------------------------------------------- #
# trace model


class TraceConfig:
    """Seeded description of a production-shaped workload.

    Args:
        duration_s: trace length (virtual seconds == replay seconds).
        base_rps: baseline request rate the envelopes modulate.
        seed: the ONE seed every draw derives from (arrivals, sizes,
            tenant mix) — the determinism contract.
        arrival: ``'poisson'`` (non-homogeneous Poisson via thinning) or
            ``'regular'`` (deterministic spacing at the instantaneous
            rate — a noise-free A/B baseline).
        diurnal_period_s / diurnal_amp: sinusoidal rate envelope
            ``1 + amp·sin(2π·t/period)`` (period defaults to the trace
            length — one "day" per trace).
        bursts: ``((start_s, duration_s, multiplier), ...)`` — flash
            load windows multiplying the instantaneous rate.
        rows_sizes / rows_alpha: request row counts and the power-law
            exponent (``p ∝ rows^-alpha`` — most requests small, the
            heavy tail real request streams have).
        tenants: tenant names (empty = single-tenant trace).
        tenant_skew: Zipf exponent over the tenant list (rank 1 hottest).
        flash_crowds: ``((start_s, duration_s, tenant_index, mass), ...)``
            — within the window, ``mass`` of the tenant mix shifts onto
            that tenant (the rest keep their relative shares).
    """

    def __init__(self, duration_s=24.0, base_rps=200.0, seed=0,
                 arrival="poisson", diurnal_period_s=None, diurnal_amp=0.15,
                 bursts=(), rows_sizes=(1, 2, 4, 8, 16, 32), rows_alpha=1.3,
                 tenants=(), tenant_skew=1.2, flash_crowds=()):
        if duration_s <= 0:
            raise ValueError(f"duration_s must be positive, got {duration_s}")
        if base_rps <= 0:
            raise ValueError(f"base_rps must be positive, got {base_rps}")
        if arrival not in ("poisson", "regular"):
            raise ValueError(f"unknown arrival {arrival!r}")
        if not 0.0 <= diurnal_amp < 1.0:
            raise ValueError(f"diurnal_amp must be in [0, 1), got {diurnal_amp}")
        if not rows_sizes:
            raise ValueError("rows_sizes must be non-empty")
        for b in bursts:
            if len(b) != 3 or b[1] <= 0 or b[2] <= 0:
                raise ValueError(f"bad burst spec {b!r}")
        for fc in flash_crowds:
            if (len(fc) != 4 or not tenants
                    or not 0 <= fc[2] < len(tenants)
                    or not 0.0 < fc[3] <= 1.0):
                raise ValueError(f"bad flash_crowd spec {fc!r}")
        self.duration_s = float(duration_s)
        self.base_rps = float(base_rps)
        self.seed = int(seed)
        self.arrival = arrival
        self.diurnal_period_s = float(diurnal_period_s
                                      if diurnal_period_s is not None
                                      else duration_s)
        self.diurnal_amp = float(diurnal_amp)
        self.bursts = tuple((float(s), float(d), float(m))
                            for s, d, m in bursts)
        self.rows_sizes = tuple(int(r) for r in rows_sizes)
        self.rows_alpha = float(rows_alpha)
        self.tenants = tuple(tenants)
        self.tenant_skew = float(tenant_skew)
        self.flash_crowds = tuple((float(s), float(d), int(i), float(m))
                                  for s, d, i, m in flash_crowds)

    def rate_at(self, t: float) -> float:
        """Instantaneous offered rate: base × diurnal × burst windows."""
        r = self.base_rps * (1.0 + self.diurnal_amp * math.sin(
            2.0 * math.pi * t / self.diurnal_period_s))
        for start, dur, mult in self.bursts:
            if start <= t < start + dur:
                r *= mult
        return max(r, 0.0)

    def peak_rate(self) -> float:
        """An upper bound on :meth:`rate_at` (the thinning envelope)."""
        peak_mult = 1.0
        for _, _, mult in self.bursts:
            peak_mult = max(peak_mult, mult)
        return self.base_rps * (1.0 + self.diurnal_amp) * peak_mult

    def _size_probs(self):
        w = [r ** -self.rows_alpha for r in self.rows_sizes]
        z = sum(w)
        return [x / z for x in w]

    def _tenant_probs(self, t: float):
        if not self.tenants:
            return None
        w = [(i + 1) ** -self.tenant_skew for i in range(len(self.tenants))]
        z = sum(w)
        probs = [x / z for x in w]
        for start, dur, idx, mass in self.flash_crowds:
            if start <= t < start + dur:
                rest = 1.0 - mass
                probs = [p * rest for p in probs]
                probs[idx] += mass
        return probs

    def to_dict(self) -> dict:
        return {
            "duration_s": self.duration_s, "base_rps": self.base_rps,
            "seed": self.seed, "arrival": self.arrival,
            "diurnal_period_s": self.diurnal_period_s,
            "diurnal_amp": self.diurnal_amp, "bursts": list(self.bursts),
            "rows_sizes": list(self.rows_sizes),
            "rows_alpha": self.rows_alpha, "tenants": list(self.tenants),
            "tenant_skew": self.tenant_skew,
            "flash_crowds": list(self.flash_crowds),
        }


class ReplayEvent:
    """One scheduled request: arrival time, row count, tenant (or None),
    and a pool pick so the replayer reuses pre-generated arrays."""

    __slots__ = ("t", "rows", "tenant", "pick")

    def __init__(self, t, rows, tenant, pick):
        self.t = t
        self.rows = rows
        self.tenant = tenant
        self.pick = pick


def generate_trace(cfg: TraceConfig):
    """Draw the full event schedule from ``cfg`` — pure function of the
    config (same config ⇒ identical schedule, sizes, tenant mix; the
    determinism test pins it).  Poisson arrivals use thinning against the
    peak-rate envelope, so the schedule is an exact non-homogeneous
    Poisson draw."""
    import numpy as np

    rng = np.random.default_rng(cfg.seed)
    size_probs = cfg._size_probs()
    size_idx = np.arange(len(cfg.rows_sizes))
    events = []
    t = 0.0
    if cfg.arrival == "poisson":
        lam = cfg.peak_rate()
        while True:
            t += float(rng.exponential(1.0 / lam))
            if t >= cfg.duration_s:
                break
            if float(rng.random()) > cfg.rate_at(t) / lam:
                continue  # thinned
            events.append(_draw_event(cfg, rng, t, size_idx, size_probs))
    else:  # regular: deterministic spacing at the instantaneous rate
        while True:
            rate = cfg.rate_at(t)
            t += 1.0 / max(rate, 1e-9)
            if t >= cfg.duration_s:
                break
            events.append(_draw_event(cfg, rng, t, size_idx, size_probs))
    return events


def _draw_event(cfg, rng, t, size_idx, size_probs):
    rows = cfg.rows_sizes[int(rng.choice(size_idx, p=size_probs))]
    tenant = None
    if cfg.tenants:
        tp = cfg._tenant_probs(t)
        tenant = cfg.tenants[int(rng.choice(len(cfg.tenants), p=tp))]
    return ReplayEvent(t, rows, tenant, int(rng.integers(0, 1 << 30)))


# --------------------------------------------------------------------- #
# replay


def replay(events, submit, *, clock=time.perf_counter, sleep=time.sleep,
           drain_timeout_s=30.0):
    """Issue ``events`` on their schedule (open loop: a backed-up system
    delays completions, never arrivals) and return one record per event:
    ``{"t", "rows", "tenant", "status", "lat_ms"}`` with ``status`` in
    ``ok`` / ``shed`` (``Overloaded`` — the bounded queue did its job) /
    ``error`` (any other failure) / ``lost`` (never resolved — always a
    bug, gated unconditionally in ``perf_regress``).

    Rollout drivers may append extra ``status="mirror"`` records for
    shadow-mirrored candidate dispatches (batcher-internal duplicates of
    client requests during a :class:`RolloutController` shadow phase).
    ``window_metrics`` classifies those separately: they are never
    counted as client ``ok``/``shed``/``error``/``lost``, never enter
    the goodput or latency numbers, and never appear in ``offered`` —
    mirrored work is capacity spent, not traffic served.

    ``submit(event) -> Future`` raises ``Overloaded`` to shed.  Latency is
    charged from the *scheduled* arrival, so queue backlog shows up in the
    numbers instead of hiding in the generator (no coordinated omission).
    """
    from dist_svgd_tpu.serving.batcher import Overloaded

    lock = threading.Lock()
    records = [None] * len(events)
    pending = []
    start = clock()

    def on_done(i, scheduled, fut):
        lat_ms = (clock() - scheduled) * 1e3
        ev = events[i]
        err = fut.exception()
        rec = {"t": ev.t, "rows": ev.rows, "tenant": ev.tenant}
        if err is None:
            rec.update(status="ok", lat_ms=lat_ms)
        elif isinstance(err, Overloaded):
            rec.update(status="shed", lat_ms=None)
        else:
            rec.update(status="error", lat_ms=None,
                       error=f"{type(err).__name__}: {err}")
        with lock:
            # first writer wins: once the drain loop has classified a
            # straggler 'lost', its late completion must not rewrite the
            # record the caller is already aggregating
            if records[i] is None:
                records[i] = rec

    for i, ev in enumerate(events):
        target = start + ev.t
        now = clock()
        if target > now:
            sleep(target - now)
            now = clock()
        scheduled = max(target, start)
        try:
            fut = submit(ev)
        except Overloaded:
            with lock:
                records[i] = {"t": ev.t, "rows": ev.rows,
                              "tenant": ev.tenant, "status": "shed",
                              "lat_ms": None}
            continue
        except Exception as e:
            with lock:
                records[i] = {"t": ev.t, "rows": ev.rows,
                              "tenant": ev.tenant, "status": "error",
                              "lat_ms": None,
                              "error": f"{type(e).__name__}: {e}"}
            continue
        pending.append(fut)
        fut.add_done_callback(
            lambda f, i=i, s=scheduled: on_done(i, s, f))
    deadline = clock() + drain_timeout_s
    for fut in pending:
        remaining = deadline - clock()
        try:
            fut.result(timeout=max(remaining, 0.001))
        except Exception:
            pass  # classification happened in the callback
    with lock:
        for i, ev in enumerate(events):
            if records[i] is None:
                records[i] = {"t": ev.t, "rows": ev.rows,
                              "tenant": ev.tenant, "status": "lost",
                              "lat_ms": None}
    return records


def window_metrics(records, t0, t1, good_ms):
    """Aggregate one ``[t0, t1)`` window of replay records.  ``goodput``
    counts completions within ``good_ms`` of their scheduled arrival —
    work the user actually experienced as served (a completion past the
    objective is capacity spent on a lost cause).

    ``status="mirror"`` records (shadow-mirrored rollout dispatches) are
    counted in their own ``mirrors`` field and excluded from every
    client-facing number — ``offered``, completions, sheds, errors,
    losses, goodput, and the latency percentiles all describe real
    client traffic only."""
    win = [r for r in records if t0 <= r["t"] < t1]
    mirrors = sum(1 for r in win if r["status"] == "mirror")
    sel = [r for r in win if r["status"] != "mirror"]
    lats = sorted(r["lat_ms"] for r in sel if r["status"] == "ok")
    good = sum(1 for r in sel
               if r["status"] == "ok" and r["lat_ms"] <= good_ms)
    span = max(t1 - t0, 1e-9)
    return {
        "offered": len(sel),
        "offered_rps": round(len(sel) / span, 1),
        "completed": len(lats),
        "shed": sum(1 for r in sel if r["status"] == "shed"),
        "errors": sum(1 for r in sel if r["status"] == "error"),
        "lost": sum(1 for r in sel if r["status"] == "lost"),
        "mirrors": mirrors,
        "good": good,
        "goodput_rps": round(good / span, 1),
        "p50_ms": round(_percentile(lats, 0.50), 3),
        "p99_ms": round(_percentile(lats, 0.99), 3),
    }


def p99_breach_seconds(records, target_ms, duration_s):
    """Seconds (1-second buckets over the trace) whose completion p99
    exceeded ``target_ms`` — plus starvation buckets (offered traffic,
    zero completions), which are the worst breach of all.  The
    ``storm_p99_breach_s`` metric: how long the tail was out of
    objective, not just whether it ever was."""
    breaches = 0
    for b in range(int(math.ceil(duration_s))):
        sel = [r for r in records if b <= r["t"] < b + 1]
        if not sel:
            continue
        lats = sorted(r["lat_ms"] for r in sel if r["status"] == "ok")
        if not lats:
            breaches += 1  # offered but nothing completed: starvation
        elif _percentile(lats, 0.99) > target_ms:
            breaches += 1
    return breaches


def time_to_recover(records, burst_end_s, target_ms, duration_s):
    """Seconds from the burst's end until the first full second that is
    healthy again (completions present, p99 at/under target, no sheds).
    Never recovering reads as the full remaining window — a pessimistic,
    gateable number instead of a silent None."""
    for b in range(int(math.ceil(burst_end_s)), int(math.ceil(duration_s))):
        sel = [r for r in records if b <= r["t"] < b + 1]
        if not sel:
            continue
        lats = sorted(r["lat_ms"] for r in sel if r["status"] == "ok")
        shed = sum(1 for r in sel if r["status"] != "ok")
        if lats and not shed and _percentile(lats, 0.99) <= target_ms:
            return round(max(b - burst_end_s, 0.0), 3)
    return round(duration_s - burst_end_s, 3)


def mirror_counts(metrics, tenant=None):
    """Batcher-internal shadow-mirror accounting from a
    ``MetricsRegistry``.  Mirrored candidate dispatches during a rollout
    shadow phase ride off the client's critical path — no replay future
    ever resolves for them — so the rollout counters are the only place
    they are visible.  Returns ``{"mirrors", "mirror_dropped",
    "mirror_errors"}``, reported *alongside* (never inside) the client
    ok/shed/error/lost numbers."""
    labels = {} if tenant is None else {"tenant": tenant}
    out = {}
    for field, name in (
            ("mirrors", "svgd_rollout_mirrors_total"),
            ("mirror_dropped", "svgd_rollout_mirror_dropped_total"),
            ("mirror_errors", "svgd_rollout_mirror_errors_total")):
        metric = metrics.get(name)
        out[field] = int(metric.value(**labels)) if metric is not None else 0
    return out


def make_submit(batcher, pools, model_registry=None):
    """The in-process ``submit(event)`` adapter: picks a pre-generated
    array of the event's size (``serve_bench.request_pool_by_size`` — the
    shared request-pool plumbing) and routes tenant events through the
    registry."""
    def submit(ev):
        pool = pools[ev.rows]
        x = pool[ev.pick % len(pool)]
        if ev.tenant is not None and model_registry is not None:
            return model_registry.submit(ev.tenant, x)
        return batcher.submit(x, tenant=ev.tenant)

    return submit


def make_http_submit(url, max_workers=32):
    """Open-loop HTTP transport for ``--url`` replay: each event posts on
    a pool thread so a slow server delays completions, not arrivals."""
    import urllib.error
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    from dist_svgd_tpu.serving.batcher import Overloaded

    pool = ThreadPoolExecutor(max_workers=max_workers)

    def post(ev, x):
        doc = {"inputs": x.tolist()}
        if ev.tenant is not None:
            doc["tenant"] = ev.tenant
        req = urllib.request.Request(
            url.rstrip("/") + "/predict", json.dumps(doc).encode(),
            {"Content-Type": "application/json"},
        )
        try:
            body = json.loads(urllib.request.urlopen(req, timeout=30).read())
        except urllib.error.HTTPError as e:
            if e.code == 429:
                raise Overloaded("shed by server (429)")
            raise
        return body.get("outputs")

    def make(pools):
        def submit(ev):
            p = pools[ev.rows]
            return pool.submit(post, ev, p[ev.pick % len(p)])

        return submit

    make.shutdown = pool.shutdown
    return make


def make_router_submit(router, max_workers=16):
    """Fleet transport for replay: each event goes through the round-15
    ``FleetRouter`` front door (health-gated failover, retries, bounded
    load) on a pool thread.  ``route`` never raises — a fleet-level 429
    (every admitted replica shedding) re-raises as ``Overloaded`` so
    ``replay`` books it shed, any other non-200 raises so it books an
    error; an admitted request must resolve."""
    from concurrent.futures import ThreadPoolExecutor

    from dist_svgd_tpu.serving.batcher import Overloaded

    pool = ThreadPoolExecutor(max_workers=max_workers)

    def post(ev, x):
        doc = {"inputs": x.tolist()}
        if ev.tenant is not None:
            doc["tenant"] = ev.tenant
        res = router.route(ev.tenant or "default",
                           json.dumps(doc).encode())
        if res.status == 429:
            raise Overloaded("shed by fleet (429)")
        if res.status != 200:
            raise RuntimeError(
                f"fleet answered {res.status} ({res.outcome})")
        body = res.json()
        return body.get("outputs") if isinstance(body, dict) else None

    def make(pools):
        def submit(ev):
            p = pools[ev.rows]
            return pool.submit(post, ev, p[ev.pick % len(p)])

        return submit

    make.shutdown = pool.shutdown
    return make


def build_fake_fleet(replicas=3, *, max_replica_rows=64, tenants=(),
                     probe_interval_s=0.2, registry=None):
    """A ``FleetRouter`` over in-process ``LoopbackReplica`` stand-ins
    with a bounded per-replica row budget — the tier-1 seam for replaying
    a trace through the fleet front door with no sockets or subprocesses
    (the same fake-transport split ``tools/fleet_drill.py`` uses).  Each
    replica sheds (429) past ``max_replica_rows`` concurrently in-flight
    rows, so a flash crowd produces real fleet-level sheds while every
    admitted request still resolves.  Returns ``(router, close)``."""
    import threading

    from dist_svgd_tpu.serving import fleet as fleet_mod
    from dist_svgd_tpu.telemetry import MetricsRegistry

    names = [f"r{i}" for i in range(int(replicas))]
    transport = fleet_mod.FakeTransport({})
    lock = threading.Lock()
    inflight = {n: 0 for n in names}

    def make_predict(name):
        def predict(inputs, tenant, headers):
            rows = len(inputs)
            with lock:
                if inflight[name] + rows > max_replica_rows:
                    raise fleet_mod.Shed("replica row budget full",
                                         retry_after_s=0.05)
                inflight[name] += rows
            try:
                time.sleep(0.0005)  # a realistic (tiny) dispatch floor
                return {"mean": [0.0] * rows}
            finally:
                with lock:
                    inflight[name] -= rows

        return predict

    for n in names:
        transport.set_replica(n, fleet_mod.LoopbackReplica(
            n, predict_fn=make_predict(n), tenants=list(tenants),
            registry=MetricsRegistry()))
    reg = registry if registry is not None else MetricsRegistry()
    replica_set = fleet_mod.ReplicaSet(
        names, transport, probe_interval_s=probe_interval_s,
        probe_timeout_s=0.2, fail_threshold=2, passive_fail_threshold=3,
        open_cooldown_s=0.5, registry=reg)
    router = fleet_mod.FleetRouter(
        names, transport=transport, replica_set=replica_set,
        max_retries=1, per_try_timeout_s=0.5, default_deadline_s=5.0,
        registry=reg)
    router.start()
    return router, router.shutdown


# --------------------------------------------------------------------- #
# the serve_storm row


def _saturated_rows_capacity(submit, pool, *, sustainable_frac=0.55,
                             clients=24, requests=360):
    """Throughput-anchored capacity probe: a saturated closed loop over
    the STEADY request mix measures the pipeline's ROW ceiling (total
    rows served over wall — a count, so host latency jitter cancels out
    of it), and ``sustainable_frac`` of that ceiling is the anchor every
    storm rate derives from.  Two failed designs inform this one:
    latency-bounded ramp probes read 4× apart run-to-run on the shared
    2-core box (its p99 jitter floor sits exactly where a health bound
    has to — a ramp's verdict at any rung is a coin flip), and a
    big-request-only saturation probe over-reads the mixed-traffic
    ceiling ~2-4× (big batches amortise the per-REQUEST Python cost that
    actually binds the steady mix).  Probing the real mix keeps the
    anchor proportional to the binding constraint however the host's
    speed swings."""
    import serve_bench

    def rows_of(item):
        arr = item[1] if isinstance(item, tuple) else item
        return arr.shape[0]

    mean_rows = sum(rows_of(it) for it in pool) / len(pool)
    # median of three spaced samples: the shared box's speed swings on a
    # seconds timescale, and a single sample anchored a whole storm to
    # whichever extreme it happened to land on
    samples = []
    for i in range(5):
        closed = serve_bench.closed_loop(submit, pool, clients,
                                         max(requests // 3, 60))
        samples.append(closed["rps"])
        if i < 4:
            time.sleep(0.75)
    samples.sort()
    return sustainable_frac * samples[2] * mean_rows


def default_lanes_max() -> int:
    """Host-derived lane ceiling for the storm's adaptive arm: extra
    dispatch lanes only help when there are cores for them to run on —
    measured on the 2-core box, 4 lanes *lose* throughput to thread
    contention (docs/notes.md round 18), so the bound follows the host."""
    return max(1, min(4, (os.cpu_count() or 2) // 2))


def run_storm(model="logreg", n_particles=4000, n_features=54, seed=0,
              steady_s=5.0, burst_s=5.0, recover_s=5.0, burst_mult=2.0,
              util=0.45, p99_target_ms=25.0, max_batch=256,
              max_queue_rows=512, base_lanes=1, base_wait_ms=2.0,
              lanes_max=None, wait_max_ms=16.0, interval_s=0.25,
              rows_sizes=(1, 2, 4, 8, 16, 32), rows_alpha=1.3,
              flash_rows_sizes=(16, 32, 64), tenants=3,
              calib_requests=400, include_static=True):
    """Measure the ``serve_storm`` row: a multi-tenant registry under the
    identical seeded steady → flash-crowd-burst → recovery trace,
    replayed against static configurations and against the autoscale
    controller — one set of warmed engines, retrace-sentried throughout.

    The burst is a **flash crowd**: one tenant (``hog``) floods the
    shared queue with heavy requests (``flash_rows_sizes``) at an offered
    ROW rate of ``burst_mult ×`` the measured base capacity, while the
    polite tenants keep their steady demand.  That is the
    millions-of-users overload shape the trace model exists for, and it
    is what makes the A/B physical rather than jitter-luck: a static
    configuration admits the flood FIFO, so every tenant's queue delay
    grows to the full bound (``max_queue_rows`` rows of backlog ahead of
    each arrival) and completions blow the objective; the controller
    tightens quotas into admission-enforced mode, keeping the hog's
    queue occupancy — and therefore EVERYONE's delay — bounded, and
    sheds the flood at arrival instead of after it has queued.

    Arms: ``static_base`` (server defaults), ``static_burst`` (the
    controller's upper window/lane bounds held always — pays the
    coalescing floor at steady), ``adaptive``.  ``value`` /
    ``storm_goodput_2x`` is the adaptive arm's whole-trace POLITE
    goodput (non-hog completions within ``p99_target_ms`` per second);
    ``storm_p99_breach_s`` / ``storm_recover_s`` are judged over the
    polite completions too.  The A/B block compares against the best
    static arm per metric.
    """
    import jax
    import numpy as np

    import serve_bench
    from tools.jaxlint.sentry import retrace_sentry

    from dist_svgd_tpu import telemetry
    from dist_svgd_tpu.serving import (
        AutoscaleController,
        AutoscalePolicy,
        ModelRegistry,
    )

    if tenants < 2:
        raise ValueError(
            "run_storm needs >= 2 tenants (a hog and at least one polite "
            f"tenant), got {tenants}; use --mode replay for single-tenant "
            "experiments"
        )
    if lanes_max is None:
        lanes_max = default_lanes_max()
    lanes_max = max(lanes_max, base_lanes)
    duration = steady_s + burst_s + recover_s
    hog = "hog"
    polite_names = [f"svc-{i}" for i in range(tenants - 1)]
    names = polite_names + [hog]

    metrics = telemetry.MetricsRegistry()
    reg = ModelRegistry(
        metrics=metrics, max_total_buckets=8 * tenants,
        max_batch=max_batch, lanes=base_lanes, max_wait_ms=base_wait_ms,
        max_queue_rows=max_queue_rows)
    rng = np.random.default_rng(seed)
    feature_dim = n_features
    for name in names:
        parts = rng.normal(size=(n_particles, 1 + feature_dim))
        reg.add_tenant(name, model, particles=parts.astype(np.float32),
                       min_bucket=8, max_bucket=max_batch,
                       quota_rows=max_queue_rows)
    reg.warm()  # every reachable bucket pre-traced, all tenants
    # settle after the warm's sustained compile burn: on a cpu-shares
    # container the burn triggers throttling that would bill a 2-4x
    # under-read into the capacity anchor (measured on the 2-core box)
    time.sleep(4.0)
    all_sizes = tuple(sorted(set(rows_sizes) | set(flash_rows_sizes)))
    pools = serve_bench.request_pool_by_size(
        feature_dim, all_sizes, per_size=32, seed=seed + 1)

    # TWO anchors, both probed THROUGH the registry, because the two
    # phases they size are bound by different constraints:
    # - the STEADY anchor replays the steady reality — tenant-interleaved
    #   heavy-tailed small requests, whose single-tenant coalescing gives
    #   run-length-one batches (measured ~5× below the blocked ceiling);
    #   the steady rate must be sustainable under exactly that penalty;
    # - the HOG anchor is one tenant's flash-size stream — long same-
    #   tenant runs coalesce into full batches, so 2× THIS ceiling is a
    #   genuine overload even for the best-batching flood imaginable.
    size_probs = TraceConfig(rows_sizes=rows_sizes,
                             rows_alpha=rows_alpha)._size_probs()
    prng = np.random.default_rng(seed + 7)
    probe_sizes = [rows_sizes[int(prng.choice(len(rows_sizes),
                                              p=size_probs))]
                   for _ in range(96)]
    steady_pool = [(names[i % len(names)], pools[r][i % len(pools[r])])
                   for i, r in enumerate(probe_sizes)]
    probe_requests = max(min(calib_requests, 240), 120)
    capacity_rows = _saturated_rows_capacity(
        lambda item: reg.submit(item[0], item[1]), steady_pool,
        requests=probe_requests)
    big = max(flash_rows_sizes)
    hog_pool = [(hog, pools[big][i % len(pools[big])]) for i in range(48)]
    hog_capacity_rows = _saturated_rows_capacity(
        lambda item: reg.submit(item[0], item[1]), hog_pool,
        requests=probe_requests)
    # cool down after the saturating probes: the container's cpu-shares
    # throttle (and any noisy neighbour) must not bill the probe's burn
    # to the first arm's steady phase
    time.sleep(2.0)
    mean_rows = sum(r * p for r, p in zip(rows_sizes, size_probs))
    capacity_rps = capacity_rows / mean_rows
    mean_flash_rows = sum(flash_rows_sizes) / len(flash_rows_sizes)
    hog_burst_rps = burst_mult * hog_capacity_rows / mean_flash_rows

    base_rps = util * capacity_rps
    cfg = TraceConfig(
        duration_s=duration, base_rps=base_rps, seed=seed,
        diurnal_amp=0.1, rows_sizes=rows_sizes, rows_alpha=rows_alpha,
        tenants=tuple(names), tenant_skew=0.5,
    )
    # the flash crowd rides a second seeded trace merged in: the hog
    # offers burst_mult × capacity in ROWS (heavy requests, uniform over
    # flash_rows_sizes) for exactly the burst window
    flash_cfg = TraceConfig(
        duration_s=burst_s, base_rps=hog_burst_rps, seed=seed + 101,
        diurnal_amp=0.0, rows_sizes=flash_rows_sizes, rows_alpha=0.0,
        tenants=(hog,),
    )
    events = generate_trace(cfg)
    for ev in generate_trace(flash_cfg):
        ev.t += steady_s
        events.append(ev)
    events.sort(key=lambda e: e.t)
    submit = make_submit(reg.batcher, pools, model_registry=reg)

    arms = {}
    if include_static:
        arms["static_base"] = dict(lanes=base_lanes, wait=base_wait_ms,
                                   adaptive=False)
        arms["static_burst"] = dict(lanes=lanes_max, wait=wait_max_ms,
                                    adaptive=False)
    arms["adaptive"] = dict(lanes=base_lanes, wait=base_wait_ms,
                            adaptive=True)

    def lat_stats(records):
        lats = sorted(r["lat_ms"] for r in records if r["status"] == "ok")
        return (round(_percentile(lats, 0.50), 3),
                round(_percentile(lats, 0.99), 3))

    results = {}
    misses_before = sum(reg.tenant(n).engine.stats()["bucket_misses"]
                        for n in names)
    with retrace_sentry("serve_storm timed replays") as sentry:
        for arm_name, arm in arms.items():
            # ONE registry across arms (fresh engines would compile inside
            # the sentried window): retune the live knobs between arms
            # through the same seams the controller uses
            reg.batcher.set_lanes(arm["lanes"])
            reg.batcher.set_max_wait_ms(arm["wait"])
            reg.batcher.set_quota_mode("overflow")
            for n in names:
                reg.set_quota(n, max_queue_rows)
            time.sleep(1.0)  # settle: don't bill the previous arm's
            # drain/teardown burn to this arm's steady phase
            controller = None
            if arm["adaptive"]:
                controller = AutoscaleController(
                    reg.batcher, metrics=metrics, model_registry=reg,
                    policy=AutoscalePolicy(
                        lanes_max=lanes_max, max_wait_ms_max=wait_max_ms,
                        p99_target_ms=p99_target_ms,
                        # the tightened per-tenant bound: a hog holds at
                        # most this share of the queue while overloaded
                        quota_tighten_frac=0.125,
                        # fast ramp (a burst eats its phase while a slow
                        # controller deliberates) but TWO consecutive
                        # overload windows to act — a single host-stall
                        # spike in one 250 ms window must not flap the
                        # knobs (measured: 17 actions/run without this)
                        cooldown_s=interval_s,
                        up_consecutive=2,
                        down_consecutive=max(2, int(0.75 / interval_s)),
                    ))
                controller.start(interval_s)
            try:
                records = replay(events, submit)
            finally:
                if controller is not None:
                    controller.stop()
            # drain between arms: the next arm's records must not queue
            # behind this one's tail
            deadline = time.monotonic() + 30.0
            while (reg.batcher.queued_rows() > 0
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            polite = [r for r in records if r["tenant"] != hog]
            hog_recs = [r for r in records if r["tenant"] == hog]
            whole = window_metrics(records, 0.0, duration, p99_target_ms)
            p_burst = window_metrics(polite, steady_s, steady_s + burst_s,
                                     p99_target_ms)
            p_burst["p50_ms"], p_burst["p99_ms"] = lat_stats(
                [r for r in polite if steady_s <= r["t"] < steady_s + burst_s])
            results[arm_name] = {
                "lanes": arm["lanes"], "max_wait_ms": arm["wait"],
                "adaptive": arm["adaptive"],
                "goodput_rps": whole["goodput_rps"],
                "polite_goodput_rps": window_metrics(
                    polite, 0.0, duration, p99_target_ms)["goodput_rps"],
                "p99_breach_s": p99_breach_seconds(
                    polite, p99_target_ms, duration),
                "recover_s": time_to_recover(
                    polite, steady_s + burst_s, p99_target_ms, duration),
                "shed": whole["shed"],
                "errors": whole["errors"],
                "lost": whole["lost"],
                "hog": {"offered": len(hog_recs),
                        "completed": sum(1 for r in hog_recs
                                         if r["status"] == "ok"),
                        "shed": sum(1 for r in hog_recs
                                    if r["status"] == "shed")},
                "phases": {
                    "steady": window_metrics(polite, 0.0, steady_s,
                                             p99_target_ms),
                    "burst_polite": p_burst,
                    "recover": window_metrics(polite, steady_s + burst_s,
                                              duration, p99_target_ms),
                },
            }
            if controller is not None:
                st = controller.status()
                results[arm_name]["controller"] = {
                    "steps": st["steps"], "actions": st["actions"],
                    "final_lanes": st["lanes"],
                    "final_max_wait_ms": st["max_wait_ms"],
                    "final_quota_scale": st["quota_scale"],
                }
    recompiles = sum(reg.tenant(n).engine.stats()["bucket_misses"]
                     for n in names) - misses_before
    reg.close(drain=True)

    adaptive = results["adaptive"]
    ab = None
    if include_static:
        # the A/B is judged on the POLITE tenants — the traffic the SLO
        # protects while a hog floods.  Total goodput is reported per arm
        # but not judged: on a host phase fast enough to absorb the flood
        # outright, a static arm "wins" total goodput by serving hostile
        # excess the controller deliberately refuses at admission, which
        # is the policy working, not a regression.
        statics = {k: v for k, v in results.items() if not v["adaptive"]}
        best_goodput = max(v["polite_goodput_rps"] for v in statics.values())
        best_breach = min(v["p99_breach_s"] for v in statics.values())
        best_recover = min(v["recover_s"] for v in statics.values())
        ab = {
            "best_static_polite_goodput_rps": best_goodput,
            "best_static_p99_breach_s": best_breach,
            "best_static_recover_s": best_recover,
            "goodput_ratio": round(
                adaptive["polite_goodput_rps"] / best_goodput, 3)
            if best_goodput else None,
            "breach_delta_s": round(
                best_breach - adaptive["p99_breach_s"], 3),
            "adaptive_wins": bool(
                adaptive["polite_goodput_rps"] > best_goodput
                and adaptive["p99_breach_s"] < best_breach),
        }

    return {
        "metric": "serve_storm",
        "unit": "good polite requests/sec over the storm",
        "platform": jax.devices()[0].platform,
        "model": model,
        "n_particles": n_particles,
        "tenants": tenants,
        "trace": {"events": len(events), "seed": seed,
                  "duration_s": duration, "steady_s": steady_s,
                  "burst_s": burst_s, "recover_s": recover_s,
                  "burst_mult": burst_mult, "util": util,
                  "base_rps": round(base_rps, 1),
                  "hog_burst_rps": round(hog_burst_rps, 1),
                  "rows_sizes": list(rows_sizes),
                  "flash_rows_sizes": list(flash_rows_sizes),
                  "rows_alpha": rows_alpha},
        "capacity_rps": round(capacity_rps, 1),
        "capacity_rows_per_s": round(capacity_rows, 1),
        "hog_capacity_rows_per_s": round(hog_capacity_rows, 1),
        "p99_target_ms": p99_target_ms,
        "max_batch": max_batch, "max_queue_rows": max_queue_rows,
        "bounds": {"lanes": [base_lanes, lanes_max],
                   "max_wait_ms": [base_wait_ms, wait_max_ms]},
        "value": adaptive["polite_goodput_rps"],
        "storm_goodput_2x": adaptive["polite_goodput_rps"],
        "storm_total_goodput_rps": adaptive["goodput_rps"],
        "storm_p99_breach_s": adaptive["p99_breach_s"],
        "storm_recover_s": adaptive["recover_s"],
        "arms": results,
        "ab": ab,
        "lost_requests": sum(v["lost"] + v["errors"]
                             for v in results.values()),
        "shed_requests": sum(v["shed"] for v in results.values()),
        "recompiles": recompiles,
        "sentry_compiles": sentry.compiles if sentry.supported else None,
    }


def storm_ok(row):
    """The unconditional ``serve_storm`` correctness gates — reasons a
    row FAILs regardless of its throughput numbers.  Returns
    ``(ok, [why...])``."""
    why = []
    if row.get("lost_requests"):
        why.append(f"{row['lost_requests']} non-shed request(s) lost or "
                   "errored — every admitted request must resolve")
    if row.get("recompiles"):
        why.append(f"{row['recompiles']} steady-state bucket recompile(s) "
                   "in the replay windows")
    if row.get("sentry_compiles"):
        why.append(f"{row['sentry_compiles']} XLA compile(s) inside the "
                   "sentried replay windows")
    for name, arm in row.get("arms", {}).items():
        phases = arm["phases"]
        total = sum(p["offered"] for p in phases.values())
        accounted = sum(p["completed"] + p["shed"] + p["errors"] + p["lost"]
                        for p in phases.values())
        if total != accounted:
            why.append(f"arm {name}: {total} offered but {accounted} "
                       "accounted — records leaked")
    return (not why), why


# --------------------------------------------------------------------- #


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("storm", "trace", "replay"),
                    default="storm")
    ap.add_argument("--model", choices=("logreg", "bnn", "gmm"),
                    default="logreg")
    ap.add_argument("--n-particles", type=int, default=4000)
    ap.add_argument("--n-features", type=int, default=54)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--steady-s", type=float, default=5.0)
    ap.add_argument("--burst-s", type=float, default=5.0)
    ap.add_argument("--recover-s", type=float, default=5.0)
    ap.add_argument("--burst-mult", type=float, default=2.0,
                    help="burst offered rate as a multiple of the "
                         "measured base-config capacity")
    ap.add_argument("--util", type=float, default=0.45,
                    help="steady offered rate as a fraction of capacity")
    ap.add_argument("--p99-target-ms", type=float, default=25.0)
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--max-queue-rows", type=int, default=512)
    ap.add_argument("--lanes-max", type=int, default=None,
                    help="adaptive lane ceiling (default: host-derived)")
    ap.add_argument("--wait-max-ms", type=float, default=16.0)
    ap.add_argument("--interval-s", type=float, default=0.25,
                    help="adaptive controller cadence")
    ap.add_argument("--rows", default="1,2,4,8,16,32",
                    help="request-size support of the heavy-tailed draw")
    ap.add_argument("--rows-alpha", type=float, default=1.3)
    ap.add_argument("--base-rps", type=float, default=200.0,
                    help="trace/replay modes: baseline rate (storm mode "
                         "calibrates its own)")
    ap.add_argument("--duration-s", type=float, default=24.0,
                    help="trace/replay modes: trace length")
    ap.add_argument("--tenants", type=int, default=3,
                    help="storm mode: tenant count (one hog + N-1 polite); "
                         "trace mode: tenant count for the skewed mix")
    ap.add_argument("--flash-rows", default="16,32,64",
                    help="storm mode: the flash crowd's heavy request "
                         "sizes")
    ap.add_argument("--url", default=None,
                    help="replay mode: live serving.server base URL "
                         "(default replays in-process)")
    ap.add_argument("--fleet", type=int, default=0,
                    help="replay mode: route through an N-replica "
                         "in-process fake fleet (FleetRouter front door) "
                         "instead of one batcher")
    args = ap.parse_args()

    rows = tuple(int(r) for r in args.rows.split(","))
    if args.mode == "storm":
        out = run_storm(
            model=args.model, n_particles=args.n_particles,
            n_features=args.n_features, seed=args.seed,
            steady_s=args.steady_s, burst_s=args.burst_s,
            recover_s=args.recover_s, burst_mult=args.burst_mult,
            util=args.util, p99_target_ms=args.p99_target_ms,
            max_batch=args.max_batch, max_queue_rows=args.max_queue_rows,
            lanes_max=args.lanes_max, wait_max_ms=args.wait_max_ms,
            interval_s=args.interval_s, rows_sizes=rows,
            rows_alpha=args.rows_alpha, tenants=args.tenants,
            flash_rows_sizes=tuple(
                int(r) for r in args.flash_rows.split(",")))
        ok, why = storm_ok(out)
        out["gates_ok"] = ok
        if not ok:
            out["gates_why"] = why
        print(json.dumps(out), flush=True)
        sys.exit(0 if ok else 1)
    cfg = TraceConfig(
        duration_s=args.duration_s, base_rps=args.base_rps, seed=args.seed,
        bursts=((args.steady_s, args.burst_s, args.burst_mult),),
        rows_sizes=rows, rows_alpha=args.rows_alpha,
        tenants=tuple(f"t{i}" for i in range(args.tenants)))
    events = generate_trace(cfg)
    if args.mode == "trace":
        print(json.dumps({"config": cfg.to_dict(), "events": len(events),
                          "head": [{"t": round(e.t, 4), "rows": e.rows,
                                    "tenant": e.tenant}
                                   for e in events[:20]]}), flush=True)
        return
    # replay mode
    import serve_bench

    from dist_svgd_tpu import telemetry
    from dist_svgd_tpu.serving import MicroBatcher

    if args.url:
        import numpy as np  # noqa: F401

        feature_dim = args.n_features
        pools = serve_bench.request_pool_by_size(
            feature_dim, rows, per_size=32, seed=args.seed + 1)
        transport = make_http_submit(args.url)
        records = replay(events, transport(pools))
        transport.shutdown(wait=False)
    elif args.fleet:
        pools = serve_bench.request_pool_by_size(
            args.n_features, rows, per_size=32, seed=args.seed + 1)
        router, close_fleet = build_fake_fleet(
            args.fleet, tenants=tuple(f"t{i}" for i in range(args.tenants)))
        transport = make_router_submit(router)
        try:
            records = replay(events, transport(pools))
        finally:
            transport.shutdown(wait=False)
            close_fleet()
    else:
        engine = serve_bench.build_engine(
            args.model, args.n_particles, args.n_features, None, args.seed,
            max_bucket=args.max_batch,
            registry=telemetry.MetricsRegistry())
        engine.warmup()
        pools = serve_bench.request_pool_by_size(
            engine.feature_dim, rows, per_size=32, seed=args.seed + 1)
        bat = MicroBatcher(engine.predict, max_batch=args.max_batch,
                           max_queue_rows=args.max_queue_rows,
                           registry=telemetry.MetricsRegistry())
        try:
            records = replay(events, make_submit(bat, pools))
        finally:
            bat.close(drain=True)
    print(json.dumps({
        "metric": "workload_replay",
        "config": cfg.to_dict(),
        "whole": window_metrics(records, 0.0, cfg.duration_s,
                                args.p99_target_ms),
        "p99_breach_s": p99_breach_seconds(records, args.p99_target_ms,
                                           cfg.duration_s),
    }), flush=True)


if __name__ == "__main__":
    main()
