"""Real-TPU validation of the fused φ paths (run on a machine with a chip).

Compares BOTH the pallas kernel (ops/pallas_svgd.py) and the jitted XLA path
(ops/svgd.py) against a float64 numpy oracle, then micro-benches them at the
10k-particle north-star scale.  Last verified on a v5e (2026-07-30):
max relerr ≤ 4.2e-5 for both paths; pallas 3.3 ms vs XLA 3.6 ms per φ at
(10k, 10k, 3) scanned (timings through the shared-pool tunnel vary ~±40%
between sessions — `bench.py` is the stable end-to-end metric).  The CPU
interpreter tests (tests/test_pallas.py) cover the math; this script covers
the Mosaic compile and real-grid semantics of both kernel variants (d=3 →
small-d broadcast distances, d=16/55 → the matmul form).
"""
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np
import jax
import jax.numpy as jnp

print("devices:", jax.devices(), flush=True)

from dist_svgd_tpu.ops.kernels import RBF
from dist_svgd_tpu.ops.pallas_svgd import phi_pallas
from dist_svgd_tpu.ops.svgd import phi


def phi_np(y, x, s, h=1.0):
    y, x, s = (np.asarray(a, dtype=np.float64) for a in (y, x, s))
    d2 = ((y[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    kt = np.exp(-d2 / h)
    drive = kt @ s
    repulse = (2.0 / h) * (y * kt.sum(1, keepdims=True) - kt @ x)
    return (drive + repulse) / x.shape[0]


xla_phi = jax.jit(lambda y, x, s: phi(y, x, s, RBF(1.0)))
rng = np.random.default_rng(0)
#  (130, 257, 7): ragged small-d at the top of the SMALL_D range — exercises
#  the sentinel-padded-column path (7 accumulated _FAR² terms + _D2_CAP
#  clamp) on real Mosaic, not just the CPU interpreter
for (k, m, d) in [(50, 37, 3), (130, 257, 7), (1024, 1024, 55), (4096, 4096, 16)]:
    y = jnp.asarray(rng.normal(size=(k, d)), dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    s = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    want = phi_np(y, x, s)
    scale = np.maximum(np.abs(want), 1e-3)
    for name, fn in [("xla", xla_phi), ("pallas", phi_pallas)]:
        got = np.asarray(fn(y, x, s))
        err = np.max(np.abs(got - want) / scale)
        print(f"({k},{m},{d}) {name:6s} max relerr {err:.3e}", flush=True)
        assert err < 1e-3, f"MISMATCH {name}"

# micro-bench at the north-star scale.  One lax.scan of K chained φ calls
# per dispatch: per-call host→device latency (many ms through a TPU tunnel)
# would otherwise swamp the ~1-3 ms kernel itself, and chaining (each φ
# feeds the next) keeps XLA from eliding any iteration.
k = m = 10_000
d = 3
K = 50
y = jnp.asarray(rng.normal(size=(k, d)), dtype=jnp.float32)
s = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
for name, fn in [("xla", xla_phi), ("pallas", phi_pallas)]:
    # an A/B check compiles once per backend variant by design (2 iterations)
    chained = jax.jit(  # jaxlint: disable=JL001
        lambda p, fn=fn: jax.lax.scan(
            lambda c, _: (c + 1e-6 * fn(c, c, s), None), p, None, length=K
        )[0]
    )
    chained(y).block_until_ready()
    t0 = time.perf_counter()
    chained(y).block_until_ready()
    dt = (time.perf_counter() - t0) / K
    print(f"{name}: {dt*1e3:.3f} ms/phi @ (10k,10k,3), scanned x{K}", flush=True)

# ---- fused Sinkhorn kernels (ops/pallas_ot.py) on real Mosaic ----------
# the CPU interpreter tests (tests/test_pallas_ot.py) cover the math; this
# covers the compiled flash-softmax accumulators, sentinel padding, and the
# end-to-end fused solve vs the XLA solve on hardware, ragged shapes incl.
import scipy.special

from dist_svgd_tpu.ops.kernels import squared_distances
from dist_svgd_tpu.ops.ot import wasserstein_grad_sinkhorn
from dist_svgd_tpu.ops.pallas_ot import (
    ctransform_reduce,
    kexp,
    plan_grad,
    sinkhorn_grad_fused,
)

for (k, m, d) in [(50, 37, 3), (1250, 10_000, 3)]:
    x = jnp.asarray(rng.normal(size=(k, d)), dtype=jnp.float32)
    yy = jnp.asarray(rng.normal(size=(m, d)) + 0.3, dtype=jnp.float32)
    p = jnp.asarray(rng.normal(size=m), dtype=jnp.float32)
    c = np.asarray(squared_distances(x, yy), dtype=np.float64)
    got = np.asarray(ctransform_reduce(x, yy, p, 1.0, soft=False))
    want = np.min(c - np.asarray(p)[None, :], axis=1)
    err_min = np.max(np.abs(got - want))
    got = np.asarray(ctransform_reduce(x, yy, p, 1.0, soft=True))
    want = scipy.special.logsumexp(np.asarray(p)[None, :] - c, axis=1)
    err_lse = np.max(np.abs(got - want))
    f = jnp.asarray(rng.normal(size=k) * 0.5, dtype=jnp.float32)
    g = jnp.asarray(rng.normal(size=m) * 0.5, dtype=jnp.float32)
    pk = np.exp(np.asarray(f)[:, None] + np.asarray(g)[None, :] - c)
    err_k = np.max(np.abs(np.asarray(kexp(x, yy, f, g, 1.0)) - pk))
    wantg = np.asarray(x) * pk.sum(1)[:, None] - pk @ np.asarray(yy)
    err_pg = np.max(np.abs(np.asarray(plan_grad(x, yy, f, g, 1.0)) - wantg)
                    / np.maximum(np.abs(wantg), 1e-3))
    print(f"({k},{m},{d}) ot-kernels: min {err_min:.2e} lse {err_lse:.2e} "
          f"kexp {err_k:.2e} plan_grad {err_pg:.2e}", flush=True)
    assert max(err_min, err_lse, err_k, err_pg) < 1e-3

    # tol=None: both paths run exactly 60 iterations, so the comparison is
    # deterministic up to roundoff — a tol exit could legitimately flip one
    # path's exit block and make an O(tol) difference look like a failure
    want = np.asarray(wasserstein_grad_sinkhorn(
        x, yy, eps=0.05, iters=60, impl="xla"))
    got = np.asarray(sinkhorn_grad_fused(x, yy, eps=0.05, iters=60))
    err = np.max(np.abs(got - want) / np.maximum(np.abs(want), 1e-3))
    print(f"({k},{m},{d}) fused-vs-xla W2 grad max relerr {err:.2e}", flush=True)
    assert err < 1e-3, "fused solve diverged from XLA solve"
print("TPU PALLAS CHECK OK", flush=True)
