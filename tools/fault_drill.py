"""Fault-recovery drill: measure the resilience subsystem end to end and
emit ONE BENCH-style ``fault_recovery`` JSON row.

The drill runs a small supervised DistSampler workload (GMM posterior — CPU
and TPU both fine; every fault is injected via ``resilience/faults.py``, so
no real signals or sleeps) through four phases:

1. **baseline** — a supervised, checkpointed run to completion (after an
   untimed warm-up of the same scan programs), giving the honest per-step
   wall and the directly-measured **checkpoint overhead** (checkpoint wall
   over segment wall at the default cadence — the acceptance gate is < 5%);
2. **kill** — the same run with an injected hard kill (``HardKillAt``,
   SIGKILL-shaped: no checkpoint, no cleanup) mid-way between checkpoints;
3. **recover** — a fresh ``RunSupervisor.run(resume=True)`` driven to the
   kill step: its wall IS the recovery cost (restore-from-latest + replay
   of the steps lost since the last periodic checkpoint);
4. **verify** — the recovered run continues to completion and the final
   particle state must be **bitwise identical** to the baseline's (the
   absolute segment grid makes resume exact — supervisor docstring), and
   one retry (transient raise) and one NaN-rollback scenario must both
   recover within budget.

Usage::

    python tools/fault_drill.py                # defaults: n=2048, S=4, 48 steps
    python tools/fault_drill.py --n 1024 --steps 96 --checkpoint-every 16
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_sampler(n, num_shards, seed=0):
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.gmm import gmm_logp
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    parts = init_particles_per_shard(seed, n, 2, num_shards)
    return dt.DistSampler(
        num_shards, lambda th, _: gmm_logp(th), None, parts,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False,
    )


def gmm_score_fn():
    """Per-θ score ``∇log p(θ)`` of the drill's GMM posterior — what the
    KSD diagnostic needs (the DistSampler's own score is sharded with its
    data, so the drill supplies the closure explicitly)."""
    import jax

    from dist_svgd_tpu.models.gmm import gmm_logp

    return jax.grad(gmm_logp)


def measure_diagnostics_overhead(n=2048, num_shards=4, num_steps=48,
                                 step_size=0.05, segment_steps=4,
                                 every_steps=16, rounds=2, seed=0):
    """Diagnostics-on vs off A/B over one warmed supervised run.

    Interleaved rounds, best-of each arm (the telemetry-overhead protocol)
    give the reported ``wall_off_s``/``wall_on_s``; the **gated**
    ``overhead_frac`` is the direct in-run fraction — the diagnostics
    passes' own wall (every compute is serial with the segment path, so
    its cost IS its wall) over the on-run's non-diagnostics wall.  Unlike
    the raw wall delta, that fraction does not inherit the pool's
    run-to-run wall noise, which on the CPU bench is ±15% — an order of
    magnitude larger than the cost being measured.  Returns the
    ``diagnostics_overhead`` row; ``tools/perf_regress.py`` FAILs it above
    a fixed 3% ceiling."""
    import time as _time

    from dist_svgd_tpu.resilience import RunSupervisor
    from dist_svgd_tpu.telemetry import MetricsRegistry
    from dist_svgd_tpu.telemetry.diagnostics import (
        DiagnosticsConfig,
        PosteriorDiagnostics,
    )

    registry = MetricsRegistry()
    ds = build_sampler(n, num_shards, seed)
    state0 = ds.state_dict()
    # ONE diagnostics instance across every on-round: its per-instance
    # jitted score program compiles once in the warm-up round, so the
    # timed rounds measure the steady-state cost, not recompilation
    diag = PosteriorDiagnostics(
        DiagnosticsConfig(every_steps=every_steps, score_fn=gmm_score_fn(),
                          row_chunk=512, max_points=512),
        registry=registry)

    diag_hist = registry.histogram("svgd_diag_compute_seconds")

    def run_once(d):
        ds.load_state_dict(state0)
        sup = RunSupervisor(ds, num_steps, step_size,
                            segment_steps=segment_steps,
                            sleep=lambda s: None, registry=registry,
                            diagnostics=d)
        diag0 = diag_hist.summary()["sum"]
        t0 = _time.perf_counter()
        sup.run()
        wall = _time.perf_counter() - t0
        return wall, diag_hist.summary()["sum"] - diag0

    run_once(None)   # warm the scan programs (untimed)
    run_once(diag)   # warm the diagnostics programs (untimed)
    best = {"off": float("inf"), "on": float("inf")}
    best_frac = float("inf")
    for _ in range(max(rounds, 1)):
        best["off"] = min(best["off"], run_once(None)[0])
        wall_on, diag_wall = run_once(diag)
        best["on"] = min(best["on"], wall_on)
        if wall_on - diag_wall > 0:
            best_frac = min(best_frac, diag_wall / (wall_on - diag_wall))
    overhead = best_frac if best_frac != float("inf") else 0.0
    return {
        "metric": "diagnostics_overhead",
        "rounds": max(rounds, 1),
        "wall_off_s": round(best["off"], 4),
        "wall_on_s": round(best["on"], 4),
        "ab_wall_delta_frac": round(
            max(0.0, best["on"] / best["off"] - 1.0)
            if best["off"] > 0 else 0.0, 4),
        "overhead_frac": round(overhead, 4),
        "n": n,
        "num_shards": num_shards,
        "num_steps": num_steps,
        "every_steps": every_steps,
    }


def run_drill(n=2048, num_shards=4, num_steps=48, step_size=0.05,
              checkpoint_every=16, segment_steps=4, kill_step=None,
              root=None, seed=0, diag_overhead=True, slo_max_ksd=50.0):
    """Run the four drill phases; returns the ``fault_recovery`` row."""
    import jax
    import numpy as np

    from dist_svgd_tpu.resilience import (
        FaultPlan,
        GuardConfig,
        HardKillAt,
        InjectNaNAt,
        RaiseAt,
        RunSupervisor,
        SimulatedHardKill,
    )
    from dist_svgd_tpu.telemetry.diagnostics import (
        DiagnosticsConfig,
        PosteriorDiagnostics,
    )
    from dist_svgd_tpu.telemetry.slo import default_training_slos

    if root is None:
        import tempfile

        root = tempfile.mkdtemp(prefix="fault_drill_")
    if kill_step is None:
        # strictly between two checkpoints: the interesting case (steps
        # actually lost; a kill ON a cadence multiple loses zero)
        kill_step = 2 * checkpoint_every + segment_steps
    if kill_step >= num_steps:
        raise ValueError(
            f"kill_step ({kill_step}) must land before num_steps "
            f"({num_steps}) or the hard kill never fires — raise --steps "
            "or pass an explicit --kill-step"
        )

    from dist_svgd_tpu.telemetry import MetricsRegistry

    # one fresh registry for the whole drill: the checkpoint/segment
    # histograms aggregate every phase (baseline + kill + recover + verify)
    registry = MetricsRegistry()

    def supervise(sampler, steps, **kw):
        kw.setdefault("segment_steps", segment_steps)
        kw.setdefault("sleep", lambda s: None)  # injected faults only
        kw.setdefault("registry", registry)
        return RunSupervisor(sampler, steps, step_size, **kw)

    # posterior diagnostics ride the baseline run: KSD (the GMM score is
    # closed-form), kernel ESS, collapse + shard divergence, every
    # checkpoint cadence — the row's ksd/ess fields are the final report
    diag = PosteriorDiagnostics(
        DiagnosticsConfig(every_steps=checkpoint_every, score_fn=gmm_score_fn(),
                          row_chunk=512, max_points=512),
        registry=registry,
    )

    # -------- phase 1: baseline (warm-up untimed, then timed) ----------- #
    ds = build_sampler(n, num_shards, seed)
    state0 = ds.state_dict()
    supervise(ds, num_steps, manager=None, diagnostics=diag).run()  # warm-up
    ds.load_state_dict(state0)
    base_dir = os.path.join(root, "baseline")
    sup = supervise(ds, num_steps, checkpoint_dir=base_dir,
                    checkpoint_every=checkpoint_every, diagnostics=diag)
    base = sup.run()
    final_baseline = np.asarray(sup.particles)
    step_wall_ms = base["segment_wall_s"] / max(base["steps_run"], 1) * 1e3
    overhead_pct = base["checkpoint_overhead_frac"] * 100
    last_diag = base["last_diagnostics"] or {}

    # diagnostics-on vs off A/B on the warmed unmanaged run: the fixed
    # ceiling perf_regress gates (diagnostics that slow training down are
    # a regression by definition, like the telemetry tracer's 3%)
    diag_overhead_frac = None
    if diag_overhead:
        diag_overhead_frac = measure_diagnostics_overhead(
            n=n, num_shards=num_shards, num_steps=num_steps,
            step_size=step_size, segment_steps=segment_steps,
            every_steps=checkpoint_every, rounds=1, seed=seed,
        )["overhead_frac"]

    # -------- phase 2: hard kill mid-run ------------------------------- #
    ds2 = build_sampler(n, num_shards, seed)
    kill_dir = os.path.join(root, "killed")
    sup2 = supervise(ds2, num_steps, checkpoint_dir=kill_dir,
                     checkpoint_every=checkpoint_every,
                     faults=FaultPlan(HardKillAt(kill_step)))
    killed_at = None
    try:
        sup2.run()
    except SimulatedHardKill:
        killed_at = sup2.t  # the boundary the kill landed on
    assert killed_at is not None, "hard kill did not fire"

    # -------- phase 3: recover (restore + replay to the kill step) ------ #
    ds3 = build_sampler(n, num_shards, seed)
    t0 = time.perf_counter()
    sup3 = supervise(ds3, killed_at, checkpoint_dir=kill_dir,
                     checkpoint_every=checkpoint_every)
    rec = sup3.run(resume=True)
    recovery_wall_s = time.perf_counter() - t0
    steps_lost = killed_at - (rec["resumed_from"] or 0)
    assert rec["steps_run"] == steps_lost, (rec, killed_at)

    # -------- phase 4: verify bitwise + the other recovery paths -------- #
    sup4 = supervise(ds3, num_steps, checkpoint_dir=kill_dir,
                     checkpoint_every=checkpoint_every)
    sup4.run(resume=True)
    bitwise = bool(np.array_equal(final_baseline, np.asarray(sup4.particles)))

    # transient raise → backoff → rollback → replay: the replayed trajectory
    # is the baseline's exactly (same ε, same grid), so final state pins it
    ds5 = build_sampler(n, num_shards, seed)
    retry = supervise(ds5, num_steps, checkpoint_dir=os.path.join(root, "r"),
                      checkpoint_every=checkpoint_every,
                      faults=FaultPlan(RaiseAt(kill_step))).run()
    retry_ok = (retry["restarts"] == 1 and retry["status"] == "completed"
                and bool(np.array_equal(final_baseline,
                                        np.asarray(ds5.particles))))

    ds6 = build_sampler(n, num_shards, seed)
    nan_rb = supervise(ds6, num_steps,
                       checkpoint_dir=os.path.join(root, "g"),
                       checkpoint_every=checkpoint_every,
                       guard=GuardConfig(),
                       faults=FaultPlan(InjectNaNAt(kill_step))).run()
    nan_ok = (nan_rb["status"] == "completed" and nan_rb["restarts"] == 1
              and nan_rb["step_size"] < step_size
              and bool(np.isfinite(np.asarray(ds6.particles)).all()))

    # training SLOs over the whole drill registry: guard trips stay within
    # budget across every phase (the NaN-rollback phase deliberately trips
    # ONE guard over dozens of segments — well inside the 0.1/segment
    # budget) and the measured KSD stays under the ceiling
    slo_doc = default_training_slos(
        registry, max_ksd=slo_max_ksd, guard_trip_budget=0.1).evaluate()

    return {
        "metric": "fault_recovery",
        "platform": jax.devices()[0].platform,
        "sampler": "distsampler",
        "n": n,
        "num_shards": num_shards,
        "num_steps": num_steps,
        "checkpoint_every": checkpoint_every,
        "segment_steps": segment_steps,
        "step_wall_ms": round(step_wall_ms, 3),
        "checkpoint_overhead_pct": round(overhead_pct, 2),
        "checkpoints": base["checkpoints"],
        "kill_step": killed_at,
        "last_checkpoint_step": rec["resumed_from"],
        "steps_lost": steps_lost,
        "recovery_wall_s": round(recovery_wall_s, 4),
        "recovery_vs_step_wall": round(
            recovery_wall_s / max(base["segment_wall_s"] / num_steps, 1e-9), 1
        ),
        "resumed_bitwise_identical": bitwise,
        "retry_backoff_recovered": bool(retry_ok),
        "nan_rollback_recovered": bool(nan_ok),
        "overhead_under_5pct": bool(overhead_pct < 5.0),
        # telemetry-registry histogram percentiles over every drill phase
        # (round 10): the same series a production scrape shows, so the
        # drill row documents the checkpoint/segment latency distribution,
        # not just the baseline-phase means above
        "checkpoint_ms_hist": registry.histogram(
            "svgd_train_checkpoint_seconds").summary(scale=1e3),
        "segment_ms_hist": registry.histogram(
            "svgd_train_segment_seconds").summary(scale=1e3),
        "restarts_total": registry.counter(
            "svgd_train_restarts_total").value(kind="transient")
        + registry.counter("svgd_train_restarts_total").value(kind="guard"),
        # posterior-health fields (round 11): the baseline run's final
        # diagnostics report (KSD needs the score — the drill's GMM has a
        # closed form; serve_bench's row carries ksd=null instead)
        "ksd": last_diag.get("ksd"),
        "ess": last_diag.get("ess"),
        "ess_frac": last_diag.get("ess_frac"),
        "min_pairwise_dist": last_diag.get("min_pairwise_dist"),
        "shard_mean_div": last_diag.get("shard_mean_div"),
        "diagnostics_per_run": registry.counter(
            "svgd_diag_computations_total").value(),
        "diagnostics_overhead": diag_overhead_frac,
        "slo_status": slo_doc["status"],
        "slo": {name: {"status": o["status"], "burn_rate": o["burn_rate"]}
                for name, o in slo_doc["objectives"].items()},
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=2048)
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--steps", type=int, default=48)
    ap.add_argument("--stepsize", type=float, default=0.05)
    ap.add_argument("--checkpoint-every", type=int, default=16)
    ap.add_argument("--segment-steps", type=int, default=4)
    ap.add_argument("--kill-step", type=int, default=None)
    ap.add_argument("--root", default=None,
                    help="checkpoint scratch root (default: a temp dir)")
    ap.add_argument("--diag-overhead", default=True,
                    action=argparse.BooleanOptionalAction,
                    help="measure the diagnostics-on/off A/B overhead "
                         "(2 warm-up + 2 timed extra unmanaged runs; "
                         "2 more timed per extra round)")
    ap.add_argument("--slo-max-ksd", type=float, default=50.0,
                    help="KSD ceiling for the row's training slo_status")
    args = ap.parse_args()

    row = run_drill(
        n=args.n, num_shards=args.shards, num_steps=args.steps,
        step_size=args.stepsize, checkpoint_every=args.checkpoint_every,
        segment_steps=args.segment_steps, kill_step=args.kill_step,
        root=args.root, diag_overhead=args.diag_overhead,
        slo_max_ksd=args.slo_max_ksd,
    )
    print(json.dumps(row), flush=True)
    ok = (row["resumed_bitwise_identical"] and row["retry_backoff_recovered"]
          and row["nan_rollback_recovered"] and row["slo_status"] == "ok")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
