"""Fault-recovery drill: measure the resilience subsystem end to end and
emit ONE BENCH-style ``fault_recovery`` JSON row.

The drill runs a small supervised DistSampler workload (GMM posterior — CPU
and TPU both fine; every fault is injected via ``resilience/faults.py``, so
no real signals or sleeps) through four phases:

1. **baseline** — a supervised, checkpointed run to completion (after an
   untimed warm-up of the same scan programs), giving the honest per-step
   wall and the directly-measured **checkpoint overhead** (checkpoint wall
   over segment wall at the default cadence — the acceptance gate is < 5%);
2. **kill** — the same run with an injected hard kill (``HardKillAt``,
   SIGKILL-shaped: no checkpoint, no cleanup) mid-way between checkpoints;
3. **recover** — a fresh ``RunSupervisor.run(resume=True)`` driven to the
   kill step: its wall IS the recovery cost (restore-from-latest + replay
   of the steps lost since the last periodic checkpoint);
4. **verify** — the recovered run continues to completion and the final
   particle state must be **bitwise identical** to the baseline's (the
   absolute segment grid makes resume exact — supervisor docstring), and
   one retry (transient raise) and one NaN-rollback scenario must both
   recover within budget.

Usage::

    python tools/fault_drill.py                # defaults: n=2048, S=4, 48 steps
    python tools/fault_drill.py --n 1024 --steps 96 --checkpoint-every 16
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_sampler(n, num_shards, seed=0):
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.gmm import gmm_logp
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    parts = init_particles_per_shard(seed, n, 2, num_shards)
    return dt.DistSampler(
        num_shards, lambda th, _: gmm_logp(th), None, parts,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False,
    )


def run_drill(n=2048, num_shards=4, num_steps=48, step_size=0.05,
              checkpoint_every=16, segment_steps=4, kill_step=None,
              root=None, seed=0):
    """Run the four drill phases; returns the ``fault_recovery`` row."""
    import jax
    import numpy as np

    from dist_svgd_tpu.resilience import (
        FaultPlan,
        GuardConfig,
        HardKillAt,
        InjectNaNAt,
        RaiseAt,
        RunSupervisor,
        SimulatedHardKill,
    )

    if root is None:
        import tempfile

        root = tempfile.mkdtemp(prefix="fault_drill_")
    if kill_step is None:
        # strictly between two checkpoints: the interesting case (steps
        # actually lost; a kill ON a cadence multiple loses zero)
        kill_step = 2 * checkpoint_every + segment_steps
    if kill_step >= num_steps:
        raise ValueError(
            f"kill_step ({kill_step}) must land before num_steps "
            f"({num_steps}) or the hard kill never fires — raise --steps "
            "or pass an explicit --kill-step"
        )

    from dist_svgd_tpu.telemetry import MetricsRegistry

    # one fresh registry for the whole drill: the checkpoint/segment
    # histograms aggregate every phase (baseline + kill + recover + verify)
    registry = MetricsRegistry()

    def supervise(sampler, steps, **kw):
        kw.setdefault("segment_steps", segment_steps)
        kw.setdefault("sleep", lambda s: None)  # injected faults only
        kw.setdefault("registry", registry)
        return RunSupervisor(sampler, steps, step_size, **kw)

    # -------- phase 1: baseline (warm-up untimed, then timed) ----------- #
    ds = build_sampler(n, num_shards, seed)
    state0 = ds.state_dict()
    supervise(ds, num_steps, manager=None).run()  # compile warm-up
    ds.load_state_dict(state0)
    base_dir = os.path.join(root, "baseline")
    sup = supervise(ds, num_steps, checkpoint_dir=base_dir,
                    checkpoint_every=checkpoint_every)
    base = sup.run()
    final_baseline = np.asarray(sup.particles)
    step_wall_ms = base["segment_wall_s"] / max(base["steps_run"], 1) * 1e3
    overhead_pct = base["checkpoint_overhead_frac"] * 100

    # -------- phase 2: hard kill mid-run ------------------------------- #
    ds2 = build_sampler(n, num_shards, seed)
    kill_dir = os.path.join(root, "killed")
    sup2 = supervise(ds2, num_steps, checkpoint_dir=kill_dir,
                     checkpoint_every=checkpoint_every,
                     faults=FaultPlan(HardKillAt(kill_step)))
    killed_at = None
    try:
        sup2.run()
    except SimulatedHardKill:
        killed_at = sup2.t  # the boundary the kill landed on
    assert killed_at is not None, "hard kill did not fire"

    # -------- phase 3: recover (restore + replay to the kill step) ------ #
    ds3 = build_sampler(n, num_shards, seed)
    t0 = time.perf_counter()
    sup3 = supervise(ds3, killed_at, checkpoint_dir=kill_dir,
                     checkpoint_every=checkpoint_every)
    rec = sup3.run(resume=True)
    recovery_wall_s = time.perf_counter() - t0
    steps_lost = killed_at - (rec["resumed_from"] or 0)
    assert rec["steps_run"] == steps_lost, (rec, killed_at)

    # -------- phase 4: verify bitwise + the other recovery paths -------- #
    sup4 = supervise(ds3, num_steps, checkpoint_dir=kill_dir,
                     checkpoint_every=checkpoint_every)
    sup4.run(resume=True)
    bitwise = bool(np.array_equal(final_baseline, np.asarray(sup4.particles)))

    # transient raise → backoff → rollback → replay: the replayed trajectory
    # is the baseline's exactly (same ε, same grid), so final state pins it
    ds5 = build_sampler(n, num_shards, seed)
    retry = supervise(ds5, num_steps, checkpoint_dir=os.path.join(root, "r"),
                      checkpoint_every=checkpoint_every,
                      faults=FaultPlan(RaiseAt(kill_step))).run()
    retry_ok = (retry["restarts"] == 1 and retry["status"] == "completed"
                and bool(np.array_equal(final_baseline,
                                        np.asarray(ds5.particles))))

    ds6 = build_sampler(n, num_shards, seed)
    nan_rb = supervise(ds6, num_steps,
                       checkpoint_dir=os.path.join(root, "g"),
                       checkpoint_every=checkpoint_every,
                       guard=GuardConfig(),
                       faults=FaultPlan(InjectNaNAt(kill_step))).run()
    nan_ok = (nan_rb["status"] == "completed" and nan_rb["restarts"] == 1
              and nan_rb["step_size"] < step_size
              and bool(np.isfinite(np.asarray(ds6.particles)).all()))

    return {
        "metric": "fault_recovery",
        "platform": jax.devices()[0].platform,
        "sampler": "distsampler",
        "n": n,
        "num_shards": num_shards,
        "num_steps": num_steps,
        "checkpoint_every": checkpoint_every,
        "segment_steps": segment_steps,
        "step_wall_ms": round(step_wall_ms, 3),
        "checkpoint_overhead_pct": round(overhead_pct, 2),
        "checkpoints": base["checkpoints"],
        "kill_step": killed_at,
        "last_checkpoint_step": rec["resumed_from"],
        "steps_lost": steps_lost,
        "recovery_wall_s": round(recovery_wall_s, 4),
        "recovery_vs_step_wall": round(
            recovery_wall_s / max(base["segment_wall_s"] / num_steps, 1e-9), 1
        ),
        "resumed_bitwise_identical": bitwise,
        "retry_backoff_recovered": bool(retry_ok),
        "nan_rollback_recovered": bool(nan_ok),
        "overhead_under_5pct": bool(overhead_pct < 5.0),
        # telemetry-registry histogram percentiles over every drill phase
        # (round 10): the same series a production scrape shows, so the
        # drill row documents the checkpoint/segment latency distribution,
        # not just the baseline-phase means above
        "checkpoint_ms_hist": registry.histogram(
            "svgd_train_checkpoint_seconds").summary(scale=1e3),
        "segment_ms_hist": registry.histogram(
            "svgd_train_segment_seconds").summary(scale=1e3),
        "restarts_total": registry.counter(
            "svgd_train_restarts_total").value(kind="transient")
        + registry.counter("svgd_train_restarts_total").value(kind="guard"),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=2048)
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--steps", type=int, default=48)
    ap.add_argument("--stepsize", type=float, default=0.05)
    ap.add_argument("--checkpoint-every", type=int, default=16)
    ap.add_argument("--segment-steps", type=int, default=4)
    ap.add_argument("--kill-step", type=int, default=None)
    ap.add_argument("--root", default=None,
                    help="checkpoint scratch root (default: a temp dir)")
    args = ap.parse_args()

    row = run_drill(
        n=args.n, num_shards=args.shards, num_steps=args.steps,
        step_size=args.stepsize, checkpoint_every=args.checkpoint_every,
        segment_steps=args.segment_steps, kill_step=args.kill_step,
        root=args.root,
    )
    print(json.dumps(row), flush=True)
    ok = (row["resumed_bitwise_identical"] and row["retry_backoff_recovered"]
          and row["nan_rollback_recovered"])
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
